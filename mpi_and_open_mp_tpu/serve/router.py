"""Fault-isolating fleet router: N worker daemons, one queue contract.

One hardened :class:`~mpi_and_open_mp_tpu.serve.daemon.ServingDaemon` is
a single failure domain — one wedge takes down the whole serving
surface, and one queue cannot drain millions-of-users traffic. This
module shards the EXISTING contract across a fleet: same
:class:`~mpi_and_open_mp_tpu.serve.queue.Ticket` state machine, same
``serve.policy`` shed vocabulary, same WAL/exit-75 semantics per worker
— the router adds placement, global admission, and failure isolation on
top, never a second request lifecycle. Four responsibilities:

**Affinity** — :class:`ConsistentHashRing` maps a request's ``session``
key to a worker through a hashlib-seeded virtual-node ring. The hash is
``sha256`` over explicit strings, never Python's salted ``hash()``, so
the mapping is identical in every process that builds the same ring —
the cross-process determinism the fleet CLI leans on (the parent
partitions a burst; each worker subprocess can recompute its own slice).
Movement on resize is structurally bounded: removing a worker moves
ONLY the sessions it owned (every other session's first clockwise point
is untouched), adding one moves only sessions that now land on the new
worker's points — expected ``sessions/(N+1)``, the bounded-movement
property PAPERS.md's process-to-node mapping work asks of a placement
function under topology change.

**Global admission** — per-worker depth/padding budgets roll up into a
single :func:`serve.policy.rollup` projection; the router's door judges
the candidate against fleet-wide depth and the merged per-bucket
padding estimate BEFORE routing, then the target worker's own door
applies its local budgets. A hot shard therefore sheds (its own
``queue-depth`` / ``padding-waste``) while cold shards keep admitting —
overload degrades one shard's tail, not the fleet.

**Work stealing** — an idle worker takes the oldest whole bucket from
the deepest backlogged worker (:meth:`FleetRouter.steal`). Whole
buckets only: a bucket is one compiled program's worth of same-shape
work, and for bitsliced shapes one 32-board plane group — splitting it
would spend two padded dispatches where one sufficed.

**Failure isolation** — workers heartbeat by pumping; a worker that
misses ``heartbeat_miss_k`` intervals is declared wedged
(:meth:`FleetRouter.check_health`), its WAL is replayed BY THE ROUTER,
and every pending/in-flight entry re-homes to the ring minus the
victim. The DESIGN.md §10 acked-loss bounds survive fleet-wide: a
re-homed ticket sheds ``re-homed`` at the source (journal frame first,
so a second replay of the victim's WAL is idempotent) and adopts under
a fresh journaled ADMIT at its new owner, so the fleet books —
``admitted == resolved + shed + re-homed-resolved`` — balance with the
request counted exactly once, at its final owner.

The router is clock-free like ``ServeQueue`` (every decision takes
``now``), owns no threads and no IO of its own, and works against any
worker handle exposing ``index`` / ``daemon`` / ``wal_path`` /
``last_beat`` / ``wedged`` — ``serve.fleet`` provides the in-process
and subprocess harnesses.
"""

from __future__ import annotations

import bisect
import hashlib

import numpy as np

from mpi_and_open_mp_tpu.robust import chaos
from mpi_and_open_mp_tpu.serve import policy as policy_mod
from mpi_and_open_mp_tpu.serve.policy import ServePolicy
from mpi_and_open_mp_tpu.serve.queue import PENDING, SHED, Ticket

#: Virtual nodes per worker. 64 points spread each worker's arc finely
#: enough that a 3-worker fleet shards a dozen sessions within ±2 of
#: even (measured in the ring property tests) while ring rebuilds stay
#: a few hundred hashes.
DEFAULT_VNODES = 64

#: Heartbeats a worker may miss before the router declares it wedged.
DEFAULT_MISS_K = 3


def _h64(s: str) -> int:
    """First 8 bytes of sha256 as an int — deterministic across
    processes and platforms (Python's builtin ``hash`` is salted per
    process; a ring built on it would shard differently in every
    worker)."""
    return int.from_bytes(hashlib.sha256(s.encode()).digest()[:8], "big")


class ConsistentHashRing:
    """Session→worker placement with bounded movement under resize.

    Each worker owns ``vnodes`` pseudo-random points on a 2^64 ring;
    a key maps to the worker owning the first point clockwise of the
    key's hash. ``seed`` salts every hash input, so independent fleets
    (or a test wanting a different shard pattern) get independent rings
    while any two processes with the same ``(workers, vnodes, seed)``
    agree exactly.
    """

    def __init__(self, workers=(), *, vnodes: int = DEFAULT_VNODES,
                 seed: int = 0):
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        self._vnodes = int(vnodes)
        self._seed = int(seed)
        self._workers: set[int] = set()
        self._points: list[tuple[int, int]] = []  # (ring point, worker)
        self._keys: list[int] = []
        for w in workers:
            self._workers.add(int(w))
        self._rebuild()

    @property
    def workers(self) -> tuple[int, ...]:
        return tuple(sorted(self._workers))

    def _rebuild(self) -> None:
        pts = []
        for w in self._workers:
            for r in range(self._vnodes):
                pts.append((_h64(f"momp-fleet/{self._seed}/w{w}/{r}"), w))
        pts.sort()
        self._points = pts
        self._keys = [p for p, _ in pts]

    def add_worker(self, worker: int) -> None:
        self._workers.add(int(worker))
        self._rebuild()

    def remove_worker(self, worker: int) -> None:
        self._workers.discard(int(worker))
        self._rebuild()

    def lookup(self, key: str) -> int:
        """The worker owning ``key``. Raises on an empty ring — routing
        with zero live workers is a fleet-down condition the caller must
        surface, not a placement question."""
        if not self._points:
            raise RuntimeError("consistent-hash ring has no live workers")
        h = _h64(f"momp-fleet/{self._seed}/key/{key}")
        i = bisect.bisect_right(self._keys, h) % len(self._points)
        return self._points[i][1]


def affinity_key(session: str | None, ticket_id: int | None = None) -> str:
    """The ring key for a request: its ``session`` when it has one, else
    a per-ticket key (no affinity to preserve — spread it)."""
    if session is not None:
        return str(session)
    return f"ticket/{ticket_id if ticket_id is not None else 0}"


class FleetRollup:
    """Merge per-worker telemetry series into fleet-wide rates/quantiles.

    The ingestion-side twin of :class:`~mpi_and_open_mp_tpu.obs.
    telemetry.WorkerTelemetry`: each shipped snapshot folds its latency-
    histogram DELTA into one fleet histogram (quantiles over the merged
    buckets — no raw samples cross the wire) and supersedes the worker's
    cumulative counters. Loss accounting is per worker by sequence
    number: ``expected = max_seq + 1`` per worker lifetime, anything
    missing (ring eviction before shipping, a frame lost to a kill)
    is ``lost`` — so ``loss()`` states exactly how much of the series
    the rollup never saw, instead of silently summing what arrived.
    """

    def __init__(self, bounds=None):
        from mpi_and_open_mp_tpu.obs import telemetry as telemetry_mod

        self.hist = telemetry_mod.LatencyHist(
            bounds if bounds is not None else telemetry_mod.DEFAULT_BOUNDS)
        #: worker → {"seq": last seq, "received": n, "counters": {...},
        #: "first_mono"/"last_mono"/"last_wall": clock stamps}.
        self.workers: dict[int, dict] = {}
        self.snapshots = 0
        self.rejected = 0
        #: Truncated sidecar frames folded in by the CLI reader — each
        #: is at most one lost interval, charged to loss() below.
        self.truncated = 0

    def ingest(self, snap: dict, *, worker=None) -> bool:
        """Fold one snapshot; False (and counted) on a schema mismatch.
        Out-of-order arrival is fine — seq gaps, not order, are loss.
        ``worker`` overrides the stream key: a recovery worker re-uses a
        surviving INDEX but restarts its sequence numbers, so its stream
        must roll up under its own key or the seq-gap loss accounting
        would read the restart as loss."""
        from mpi_and_open_mp_tpu.obs import telemetry as telemetry_mod

        if (not isinstance(snap, dict)
                or snap.get("v") != telemetry_mod.SNAPSHOT_SCHEMA):
            self.rejected += 1
            return False
        w = int(snap["worker"]) if worker is None else worker
        st = self.workers.setdefault(w, {
            "seq": -1, "received": 0, "counters": {},
            "first_mono": float(snap["mono"]),
            "last_mono": float(snap["mono"]),
            "last_wall": float(snap["wall"]),
        })
        st["received"] += 1
        if snap["seq"] > st["seq"]:
            st["seq"] = int(snap["seq"])
            st["counters"] = dict(snap.get("counters") or {})
            st["last_mono"] = float(snap["mono"])
            st["last_wall"] = float(snap["wall"])
        st["first_mono"] = min(st["first_mono"], float(snap["mono"]))
        self.hist.merge_counts(snap.get("hist") or {})
        self.snapshots += 1
        return True

    def counter(self, name: str) -> float:
        """Fleet-wide sum of a cumulative counter's latest value."""
        return sum(st["counters"].get(name, 0)
                   for st in self.workers.values())

    def rate(self, name: str) -> float:
        """Fleet-wide rate: the summed counter over the widest
        first→last snapshot span any worker covered (one shared clock
        in-process; per-process monotonic spans are still each worker's
        own honest denominator cross-process)."""
        span = max((st["last_mono"] - st["first_mono"]
                    for st in self.workers.values()), default=0.0)
        if span <= 0:
            return 0.0
        return self.counter(name) / span

    def quantile(self, q: float) -> float:
        return self.hist.quantile(q)

    def loss(self) -> dict:
        """Snapshot-loss accounting: per-worker seq gaps plus truncated
        sidecar frames, over everything the workers ever numbered."""
        expected = sum(st["seq"] + 1 for st in self.workers.values())
        received = sum(st["received"] for st in self.workers.values())
        lost = max(expected - received, 0) + self.truncated
        expected += self.truncated
        return {
            "expected": expected, "received": received, "lost": lost,
            "truncated": self.truncated,
            "frac": round(lost / expected, 6) if expected else 0.0,
        }

    def clock_offsets(self) -> dict[int, float]:
        """Per-worker monotonic→wall offsets from the latest heartbeat
        exchange pair — the alignment the merged timeline applies."""
        return {w: round(st["last_wall"] - st["last_mono"], 6)
                for w, st in self.workers.items()}

    def summary(self) -> dict:
        h = self.hist.to_dict()
        return {
            "workers": sorted(self.workers, key=str),
            "snapshots": self.snapshots,
            "rejected": self.rejected,
            "resolved": self.counter("resolved"),
            "shed": self.counter("shed"),
            "resolved_rps": round(self.rate("resolved"), 3),
            "p50_s": h["p50_s"], "p99_s": h["p99_s"],
            "p999_s": h["p999_s"],
            "hist_count": h["count"],
            "loss": self.loss(),
        }


class FleetRouter:
    """The fault-isolating front of a worker fleet.

    ``workers`` are handles with ``index`` (stable int id), ``daemon``
    (a :class:`ServingDaemon`), ``wal_path`` (``None`` = re-home from
    the live queue instead of a journal replay), ``last_beat``
    (caller-maintained monotonic stamp) and ``wedged`` (set by the
    router, never cleared — a wedged worker leaves the fleet). The
    router never advances clocks: the fleet loop stamps beats and
    passes ``now``.
    """

    def __init__(self, workers, *, vnodes: int = DEFAULT_VNODES,
                 seed: int = 0, heartbeat_interval_s: float = 0.05,
                 heartbeat_miss_k: int = DEFAULT_MISS_K):
        ws = list(workers)
        if not ws:
            raise ValueError("FleetRouter needs at least one worker")
        if heartbeat_miss_k < 1:
            raise ValueError(
                f"heartbeat_miss_k must be >= 1, got {heartbeat_miss_k}")
        self._workers: dict[int, object] = {w.index: w for w in ws}
        if len(self._workers) != len(ws):
            raise ValueError("worker indices must be unique")
        self.ring = ConsistentHashRing(self._workers, vnodes=vnodes,
                                       seed=seed)
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.heartbeat_miss_k = int(heartbeat_miss_k)
        self._rollup = policy_mod.rollup(
            w.daemon.policy for w in self.live_workers())
        #: The fleet-wide telemetry aggregator: the fleet loop ships
        #: each worker's snapshots here (in-process piggybacked on the
        #: heartbeat; cross-process read back from the sidecar streams).
        self.telemetry = FleetRollup()
        # Door accounting: submissions the ROUTER refused before any
        # worker saw them (fleet-wide budget breach).
        self.door_shed: dict[str, int] = {}
        self.submitted = 0
        self.rehomes = 0  # re-home MOVES (one ticket moved twice = 2)
        self.pool_rehomed = 0  # resident sessions moved off wedged workers
        self.steals = 0
        self.rejoins = 0
        self.drains = 0
        self.wedged_workers: list[int] = []
        self.drained_workers: list[int] = []
        #: Tickets adopted during the most recent wedge re-home — the
        #: bench kill drill reads their ``resolved_at`` stamps to
        #: measure recovery time.
        self.last_rehomed: list[Ticket] = []
        #: Whole buckets released by a donor but not yet adopted by the
        #: thief — the transfer window of a deferred steal. The door
        #: counts these against the fleet (they are admitted work) while
        #: neither worker's queue holds them, so a stolen bucket is
        #: counted against exactly ONE owner at every instant: donor
        #: before release, this ledger in transit, thief after adopt.
        self._in_transit: list[dict] = []
        #: Handles replaced by a REJOIN — their queues still hold the
        #: shed/resolved history of the pre-failure lifetime, which the
        #: fleet books must keep counting (a rejoin is a new lifetime
        #: for the INDEX, not an amnesty for the old one's ledger).
        self._retired: list = []
        #: Session → worker-index directory. The ring names a session's
        #: BIRTH worker; whole-slab-group migration (drain, rejoin
        #: claims) may land a session off its ring point, and the verbs
        #: must follow the session, not the hash.
        self._session_home: dict[str, int] = {}

    # -- topology ----------------------------------------------------------

    def live_workers(self) -> list:
        return [w for w in self._workers.values()
                if not w.wedged and not getattr(w, "drained", False)]

    def worker(self, index: int):
        return self._workers[index]

    def _recompute_rollup(self) -> None:
        live = self.live_workers()
        if live:
            self._rollup = policy_mod.rollup(w.daemon.policy for w in live)

    def add_worker(self, worker) -> None:
        """Admit a worker to the fleet mid-burst: into the worker table,
        onto the ring (bounded movement — only sessions landing on the
        new worker's points move), and — the part that used to be
        missed — into the admission projection: the door's rolled-up
        depth budget must widen the moment capacity joins, exactly as it
        narrows on a wedge, or the fleet sheds against yesterday's
        fleet size."""
        from mpi_and_open_mp_tpu.obs import trace

        index = int(worker.index)
        if index in self._workers:
            raise ValueError(f"worker index {index} already in the fleet")
        self._workers[index] = worker
        self.ring.add_worker(index)
        self._recompute_rollup()
        trace.event("serve.fleet.join", worker=index,
                    live=len(self.live_workers()))

    def rejoin_worker(self, worker, now: float) -> int:
        """Re-admit a recovered worker under its old index — the
        membership inverse of :meth:`declare_wedged` and the missing
        half of :meth:`add_worker`.

        Three rungs, in order. (1) **Ledger continuity**: the failed
        lifetime's handle retires but its queue keeps counting in
        :meth:`books` — a rejoin is a new lifetime for the index, never
        an amnesty for the old one's re-homed sheds. (2) **Bounded
        ring re-entry**: the index returns to its OLD ring points
        (``_h64`` is a pure function of ``(seed, index, replica)``), so
        exactly the keys that left when it wedged come back — expected
        ``sessions/(N+1)`` movement, nothing else shifts. (3) **The
        claim pass**: every whole slab group whose lead session now
        lands on the rejoiner's points migrates back — journaled
        destination-first (``adopt_session`` writes CREATE+STEP on the
        rejoiner's WAL, the ``post-rejoin`` crash site fires between
        the handshake halves, then the donor's EVICT closes its books)
        and bit-exact (the claim carries the ORIGIN create board plus
        the journaled step total; the rejoiner's device replays the
        advance). Pending tickets do NOT move — they finish at their
        current owners; only placement-sticky resident state follows
        the ring. Returns the number of sessions claimed.

        The caller hands in a FRESH handle (new daemon resumed from the
        victim's own journal — which a completed wedge re-home left
        empty, so the rejoiner adopts nothing it no longer owns) and is
        responsible for the warming heartbeat cover while the rejoiner
        fills its AOT cache (``serve.fleet`` stamps ``warming`` handles
        in the shared post-round beat)."""
        from mpi_and_open_mp_tpu.obs import metrics, trace

        index = int(worker.index)
        old = self._workers.get(index)
        if old is worker:
            raise ValueError(
                f"worker {index} rejoin needs a fresh handle, not the "
                "failed lifetime's own")
        if old is not None:
            if not (old.wedged or getattr(old, "drained", False)):
                raise ValueError(
                    f"worker {index} is live; rejoin re-admits a wedged "
                    "or drained worker (add_worker admits new ones)")
            self._retired.append(old)
        if worker.wedged or getattr(worker, "drained", False):
            raise ValueError(
                f"worker {index} rejoin handle arrives pre-failed")
        self._workers[index] = worker
        self.ring.add_worker(index)
        self._recompute_rollup()
        claimed = self._claim_sessions(worker, now)
        self.rejoins += 1
        metrics.inc("serve.fleet.rejoins")
        trace.event("serve.fleet.rejoin", worker=index, claimed=claimed,
                    live=len(self.live_workers()))
        return claimed

    def _claim_sessions(self, dest, now: float) -> int:
        """Move every whole slab group whose LEAD session's ring
        affinity is ``dest`` from its current owner. Whole groups only:
        slab-mates advance under one donated dispatch, and the lead
        (first-created) session decides the group's placement so one
        hash lookup moves one program's worth of state."""
        claimed = 0
        for src in list(self.live_workers()):
            if src.index == dest.index:
                continue
            groups = (src.daemon.pool.slab_groups()
                      if src.daemon._pool is not None
                      else {None: list(src.daemon._session_log)})
            for _, sids in groups.items():
                sids = [s for s in sids if s in src.daemon._session_log]
                if not sids:
                    continue
                if self.ring.lookup(str(sids[0])) != dest.index:
                    continue
                for sid in sids:
                    self._migrate_session(src, dest, sid)
                    claimed += 1
        return claimed

    def _migrate_session(self, src, dest, sid: str) -> None:
        """One session's membership move, destination-journal-first:
        the dest WAL gets a fresh CREATE+STEP lifetime (bit-exact —
        origin create board + journaled step total), then the source's
        EVICT frame closes its books. A crash between the halves leaves
        the session live in BOTH journals with identical resumable
        state: duplicated, never lost."""
        entry = src.daemon._session_log[sid]
        dest.daemon.adopt_session(sid, entry["board"], int(entry["steps"]))
        src.daemon.evict_session(sid)
        self._session_home[str(sid)] = dest.index
        self.pool_rehomed += 1

    # -- routing + global admission ----------------------------------------

    def target_for(self, session: str | None) -> int:
        """Affinity worker index for a session (ring over LIVE workers
        only — wedged workers left the ring when declared)."""
        return self.ring.lookup(affinity_key(session, self.submitted))

    def submit(self, board, steps: int, now: float,
               session: str | None = None) -> Ticket:
        """Route one request. Door order: (1) fleet-wide budget — the
        rolled-up depth cap and the padding estimate over every live
        worker's pending buckets plus the candidate; (2) the affinity
        worker's own door (its local depth/padding budgets — the
        hot-shard shed). Always returns a ticket; a router-door shed is
        terminal with the standard vocabulary reason, owned by no
        worker (it never existed anywhere worth replaying)."""
        self.submitted += 1
        board = np.asarray(board)
        target = self._workers[self.target_for(session)]
        reason = self._door_verdict(board, steps, target)
        if reason is not None:
            self.door_shed[reason] = self.door_shed.get(reason, 0) + 1
            t = Ticket(-self.submitted, board, int(steps), float(now),
                       state=SHED, reason=reason, resolved_at=float(now),
                       session=session)
            return t
        return target.daemon.submit(board, steps, session=session)

    def _door_verdict(self, board, steps: int, target) -> str | None:
        depth = 0
        counts: dict[tuple, int] = {}
        widths: dict[tuple, int | None] = {}
        for w in self.live_workers():
            q = w.daemon.queue
            depth += q.depth()
            for key, n in q._bucket_counts().items():
                counts[key] = counts.get(key, 0) + n
                widths.setdefault(key, q._slice_width(key))
        # Buckets parked in a steal/drain transfer window belong to the
        # fleet but to NEITHER queue right now — without this the door
        # would judge a depth that forgets admitted work mid-move (the
        # historical bug was worse: the synchronous steal double-counted
        # the bucket at donor AND thief for one round of estimates).
        for parked in self._in_transit:
            for e in parked["entries"]:
                b = np.asarray(e["board"])
                key = (b.shape, b.dtype.str, int(e["steps"]),
                       str(e.get("workload", "life")))
                depth += 1
                counts[key] = counts.get(key, 0) + 1
                widths.setdefault(key,
                                  target.daemon.queue._slice_width(key))
        cand = ((board.shape, board.dtype.str, int(steps)))
        counts[cand] = counts.get(cand, 0) + 1
        widths.setdefault(cand, target.daemon.queue._slice_width(cand))
        return policy_mod.admit(
            self._rollup, depth,
            [(n, widths[key]) for key, n in counts.items()])

    # -- device-resident sessions ------------------------------------------
    #
    # The consistent-hash ring IS the session→worker pool map: a
    # session's boards live in exactly one worker's device pool, the one
    # its key hashes to. These methods route the four lifecycle verbs;
    # a wedge re-homes the sessions themselves (create board + journaled
    # step total — one board crosses the wire, the destination's device
    # replays the advance).

    def _home_worker(self, session: str):
        """The worker actually holding ``session``. The directory
        (``_session_home``) wins over the ring: whole-slab-group moves
        (drain, rejoin claims) may place a session off its hash point,
        and a verb routed by hash alone would miss it."""
        sid = str(session)
        idx = self._session_home.get(sid)
        if idx is not None:
            w = self._workers.get(idx)
            if (w is not None and not w.wedged
                    and not getattr(w, "drained", False)):
                return w
        return self._workers[self.ring.lookup(sid)]

    def create_session(self, session: str, board, now: float):
        w = self._workers[self.ring.lookup(str(session))]
        handle = w.daemon.create_session(session, board)
        self._session_home[str(session)] = w.index
        return handle

    def step_session(self, session: str, steps: int, now: float) -> Ticket:
        # A resident step is a submission like any other: it admits a
        # ticket at its home worker, and the books identity
        # ``submitted == admitted + door_shed`` must keep holding when
        # traffic mixes one-shot boards with session steps.
        self.submitted += 1
        return self._home_worker(session).daemon \
            .submit_session(session, steps)

    def snapshot_session(self, session: str):
        return self._home_worker(session).daemon.snapshot_session(session)

    def evict_session(self, session: str):
        board = self._home_worker(session).daemon.evict_session(session)
        self._session_home.pop(str(session), None)
        return board

    # -- failure isolation -------------------------------------------------

    def check_health(self, now: float) -> list[int]:
        """Declare every worker whose beat is older than
        ``miss_k * interval`` wedged and re-home its pending set.
        Returns the indices declared THIS call."""
        horizon = self.heartbeat_miss_k * self.heartbeat_interval_s
        declared = []
        for w in list(self.live_workers()):
            if len(self.live_workers()) <= 1:
                break  # nobody left to re-home onto
            if now - w.last_beat > horizon:
                self.declare_wedged(w.index, now)
                declared.append(w.index)
        return declared

    def declare_wedged(self, index: int, now: float) -> list[Ticket]:
        """The isolation ladder for one failed worker: out of the ring →
        WAL replay (the durable truth; the live queue only cross-checks
        it) → ``re-homed`` sheds journaled back to the victim → adoption
        on the survivors by consistent hash. Returns the adopted
        tickets (also kept in :attr:`last_rehomed`)."""
        from mpi_and_open_mp_tpu.obs import metrics, trace

        victim = self._workers[index]
        if victim.wedged:
            return []
        survivors = [w for w in self.live_workers() if w.index != index]
        if not survivors:
            raise RuntimeError(
                f"worker {index} wedged with no survivors to re-home to")
        victim.wedged = True
        self.ring.remove_worker(index)
        self.wedged_workers.append(index)
        self._recompute_rollup()

        # The whole re-home runs under chaos suppression — it is a
        # RECOVERY path, and by the repo's convention (daemon fallback
        # engines, fleet CLI strip_chaos) the fault that killed the
        # victim must not re-kill the redo. Planned membership moves
        # (rejoin claims, graceful drains) stay instrumented: their
        # ``post-rejoin``/``mid-drain`` sites fire outside this block.
        with chaos.suppressed():
            entries, pool_sessions = self._drain_victim(victim, now)
            adopted: list[Ticket] = []
            by_target: dict[int, list[dict]] = {}
            for e in entries:
                key = affinity_key(e.get("session"), e.get("id"))
                by_target.setdefault(self.ring.lookup(key), []).append(e)
            for tgt_index, group in by_target.items():
                adopted.extend(
                    self._workers[tgt_index].daemon.adopt(group, now))
            # Re-home the victim's RESIDENT SESSIONS: the ring minus the
            # victim names each session's new pool, and adopt_session
            # journals a fresh CREATE+STEP lifetime there before the
            # destination device replays the advance — the re-home
            # carries a snapshot-equivalent (create board + step total),
            # never the raw slab.
            for sid, entry in pool_sessions.items():
                tgt = self._workers[self.ring.lookup(str(sid))]
                tgt.daemon.adopt_session(sid, entry["board"],
                                         int(entry["steps"]))
                self._session_home[str(sid)] = tgt.index
                # Close the victim's books: an EVICT frame per moved
                # session (the pool twin of the re-homed SHED) makes a
                # second replay of the victim's journal find nothing
                # live.
                if victim.daemon._wal is not None:
                    victim.daemon._wal.pool_evict(sid)
                victim.daemon._session_log.pop(sid, None)
                self.pool_rehomed += 1
        self.rehomes += len(entries)
        self.last_rehomed = adopted
        metrics.inc("serve.fleet.wedged")
        metrics.inc("serve.fleet.rehomed", len(entries))
        if pool_sessions:
            metrics.inc("serve.fleet.pool_rehomed", len(pool_sessions))
        trace.event("serve.fleet.wedged", worker=index,
                    rehomed=len(entries), pool=len(pool_sessions),
                    survivors=len(survivors))
        return adopted

    def _drain_victim(self, victim, now: float) -> tuple[list[dict], dict]:
        """The victim's outstanding entries, from its journal when it
        has one (a wedged process's memory is not trustworthy; its WAL
        is), else from the live queue. Either way the victim's own books
        close: every drained ticket sheds ``re-homed`` in its queue and
        — via :meth:`ServingDaemon.release` — in its journal, so a
        second replay finds nothing pending. Returns ``(entries,
        pool_sessions)``: the second element is the victim's live
        resident-session map (WAL-replayed ``{sid: {board, steps,
        wall}}``; the in-memory session log when there is no journal)."""
        from mpi_and_open_mp_tpu.serve import wal as wal_mod

        pending = victim.daemon.queue.pending()
        if victim.wal_path is None:
            return (victim.daemon.release(pending, now),
                    dict(victim.daemon._session_log))
        rep = wal_mod.replay(victim.wal_path)
        # Close the in-memory books with the same re-homed sheds (this
        # also appends the SHED frames that make the journal replay
        # idempotent). In-process the two views must agree; the journal
        # wins on any disagreement because it is what a cross-process
        # recovery would see.
        victim.daemon.release(pending, now)
        entries = []
        for e in rep.pending:
            entries.append({
                "id": e["id"], "board": e["board"], "steps": e["steps"],
                "session": e.get("session"), "wall": e.get("wall", 0.0),
                "queued_s": e.get("queued_s", 0.0),
            })
        return entries, rep.pool_sessions

    # -- graceful drain ----------------------------------------------------

    def drain_worker(self, index: int, now: float) -> dict:
        """Gracefully remove a LIVE worker — the planned inverse of
        :meth:`declare_wedged`, with the luxury a wedge never has: the
        worker is still trustworthy, so the handoff can be ordered for
        zero loss instead of reconstructed from a journal post mortem.

        The ladder: (1) **cordon** — off the ring and out of the
        rolled-up door budget, so no new work routes to it while its
        backlog unwinds; (2) **board buckets migrate whole** — each
        pending bucket adopts at ONE survivor picked by its lead
        ticket's affinity, destination journal first (the ``mid-drain``
        crash site fires between the adopt and the source's
        ``re-homed`` SHED — a kill there duplicates one bucket, never
        loses it); (3) **resident-step tickets finish locally** — their
        STEP frames are already journaled and authoritative here, so
        they dispatch before the pool moves rather than risk a
        double-apply; (4) **resident sessions migrate whole slab
        groups** (never splitting one — slab-mates share a donated
        dispatch) to each group's lead-session affinity; (5) **WAL
        compact + handoff** — the drained journal rotates around its
        now-empty pending set and syncs, so the handoff receipt is
        durable: a later replay of the drained worker's journal finds
        nothing live. Returns the migration stats dict."""
        from mpi_and_open_mp_tpu.obs import metrics, trace

        victim = self._workers[index]
        if victim.wedged or getattr(victim, "drained", False):
            raise ValueError(
                f"worker {index} already left the fleet; drain is for "
                "live workers (a wedge is declared, not drained)")
        survivors = [w for w in self.live_workers() if w.index != index]
        if not survivors:
            raise RuntimeError(
                f"cannot drain worker {index}: no survivors to adopt "
                "its work")
        # (1) Cordon at the door: off the ring, out of the rollup. The
        # worker stays pumpable (not wedged/drained yet) so its pool
        # tickets can finish below.
        victim.cordoned = True
        self.ring.remove_worker(index)
        self._recompute_rollup_excluding(index)
        trace.event("serve.fleet.cordon", worker=index)

        # (2) Whole board buckets, destination-journal-first.
        moved_tickets = 0
        for key, group in list(victim.daemon.queue.buckets().items()):
            if key[0] == "pool":
                continue
            lead = group[0]
            tgt = self._workers[self.ring.lookup(
                affinity_key(lead.session, lead.id))]
            entries = victim.daemon.export(group, now)
            tgt.daemon.adopt(entries, now)
            # Instrumented crash site: the bucket is journaled at the
            # destination, the source's re-homed SHED is not — a kill
            # here re-dispatches the bucket at both on recovery
            # (duplicated, dispatch is pure) instead of at neither.
            if chaos.crash_armed("mid-drain"):
                chaos.crash_now()
            victim.daemon._shed_batch(group, policy_mod.SHED_REHOMED, now)
            moved_tickets += len(entries)
            self.rehomes += len(entries)

        # (3) Resident-step tickets finish here: their journaled STEP
        # frames are authoritative on THIS worker until the session
        # moves; migrating the session below carries their effect.
        rounds = 0
        while any(t.handle is not None
                  for t in victim.daemon.queue.pending()):
            victim.daemon.pump(now, drain=True)
            rounds += 1
            if rounds > 1000:
                raise RuntimeError(
                    f"worker {index} failed to finish its resident-step "
                    "tickets while draining")

        # (4) Resident sessions, whole slab groups, lead-session
        # affinity.
        moved_sessions = 0
        groups = (victim.daemon.pool.slab_groups()
                  if victim.daemon._pool is not None
                  else {None: list(victim.daemon._session_log)})
        for _, sids in groups.items():
            sids = [s for s in sids if s in victim.daemon._session_log]
            if not sids:
                continue
            tgt = self._workers[self.ring.lookup(str(sids[0]))]
            for sid in sids:
                self._migrate_session(victim, tgt, sid)
                moved_sessions += 1

        # (5) Compact + hand off the journal: the rotation snapshot is
        # the receipt — pending and pool both empty, durably.
        if victim.daemon._wal is not None:
            victim.daemon._compact_wal()
            victim.daemon._wal.sync()
        victim.drained = True
        self.drains += 1
        self.drained_workers.append(index)
        metrics.inc("serve.fleet.drains")
        trace.event("serve.fleet.drained", worker=index,
                    tickets=moved_tickets, sessions=moved_sessions,
                    survivors=len(survivors))
        return {"worker": index, "tickets_moved": moved_tickets,
                "sessions_moved": moved_sessions,
                "survivors": len(survivors)}

    def _recompute_rollup_excluding(self, index: int) -> None:
        live = [w for w in self.live_workers()
                if w.index != index and not getattr(w, "cordoned", False)]
        if live:
            self._rollup = policy_mod.rollup(w.daemon.policy for w in live)

    # -- work stealing -----------------------------------------------------

    def steal(self, now: float, *, defer: bool = False) -> int:
        """Move the oldest whole bucket from the deepest backlogged
        worker to an idle one. Whole buckets only — a bucket is one
        compiled program's worth of same-shape work (one 32-board plane
        group when bitsliced); splitting it buys a second padded
        dispatch for zero latency win. The donor keeps at least one
        bucket (stealing its last one just moves the wait). Returns the
        number of tickets moved (0 = no steal this round).

        The move is two-phase: the donor releases the bucket into the
        router's in-transit ledger, then the thief adopts it from
        there. Between the phases the bucket is counted against the
        LEDGER at the door (see :meth:`_door_verdict`) and against
        neither queue — so a stolen bucket has exactly one owner at
        every instant, where the old synchronous move briefly showed
        the same depth at donor and thief. ``defer=True`` stops after
        the park (the fleet pump delivers at the next round start, so
        the thief's door estimate settles before it adopts);
        ``defer=False`` keeps the synchronous contract for direct
        callers by delivering immediately."""
        live = self.live_workers()
        idle = [w for w in live if w.daemon.queue.depth() == 0]
        if not idle:
            return 0
        donors = [(w.daemon.queue.depth(), w) for w in live
                  if len(w.daemon.queue.buckets()) >= 2]
        if not donors:
            return 0
        _, donor = max(donors, key=lambda dw: dw[0])
        buckets = donor.daemon.queue.buckets()
        # Oldest lead ticket first: that bucket has waited longest and
        # the idle worker will dispatch it immediately.
        _, group = min(buckets.items(), key=lambda kv: kv[1][0].id)
        thief = min(idle, key=lambda w: w.index)
        entries = donor.daemon.release(group, now)
        self._in_transit.append({
            "entries": entries, "donor": donor.index,
            "thief": thief.index,
        })
        moved = len(entries)
        if not defer:
            self.deliver_in_transit(now)
        return moved

    def deliver_in_transit(self, now: float) -> int:
        """Land every parked steal at its thief. If the thief left the
        fleet while the bucket was in transit (wedged or drained
        between park and delivery), the bucket re-routes by its lead
        entry's ring affinity — parked work is admitted work; it never
        evaporates with its intended recipient. Returns tickets
        delivered."""
        from mpi_and_open_mp_tpu.obs import metrics, trace

        delivered = 0
        parked, self._in_transit = self._in_transit, []
        for move in parked:
            entries = move["entries"]
            thief = self._workers.get(move["thief"])
            if (thief is None or thief.wedged
                    or getattr(thief, "drained", False)):
                lead = entries[0]
                thief = self._workers[self.ring.lookup(
                    affinity_key(lead.get("session"), lead.get("id")))]
            thief.daemon.adopt(entries, now)
            delivered += len(entries)
            self.steals += 1
            self.rehomes += len(entries)
            metrics.inc("serve.fleet.steals")
            trace.event("serve.fleet.steal", donor=move["donor"],
                        thief=thief.index, tickets=len(entries))
        return delivered

    def in_transit_depth(self) -> int:
        """Tickets parked between a donor's release and the thief's
        adopt. Part of the fleet's pending surface: drain loops must not
        declare the fleet empty while a bucket is mid-move."""
        return sum(len(m["entries"]) for m in self._in_transit)

    # -- accounting --------------------------------------------------------

    def books(self) -> dict:
        """Fleet-wide accounting across every worker that ever held a
        ticket — including handles retired by a REJOIN, whose queues
        still carry the failed lifetime's history. Each request is
        counted once, at its final owner: a re-home is one ``re-homed``
        shed at the source plus one adopted ticket at the destination
        (or one parked in-transit entry mid-steal), and the two must
        cancel — ``balanced`` asserts the shed/adopt pairing and the
        ISSUE equation ``admitted == resolved + shed + pending`` with
        re-homed moves netted out and the in-transit window counted as
        pending-elsewhere."""
        admitted = resolved = shed_real = rehomed_shed = pending = 0
        adopted = rehomed_resolved = 0
        for w in list(self._workers.values()) + list(self._retired):
            for t in w.daemon.queue.tickets():
                if t.resumed:
                    adopted += 1
                else:
                    admitted += 1
                if t.state == PENDING:
                    pending += 1
                elif t.reason == policy_mod.SHED_REHOMED:
                    rehomed_shed += 1
                elif t.state == SHED:
                    shed_real += 1
                else:
                    resolved += 1
                    if t.resumed:
                        rehomed_resolved += 1
        door = sum(self.door_shed.values())
        in_transit = self.in_transit_depth()
        return {
            "submitted": self.submitted,
            "door_shed": door,
            "admitted": admitted,
            "resolved": resolved,
            "shed": shed_real,
            "pending": pending,
            "rehomed": rehomed_shed,
            "rehomed_resolved": rehomed_resolved,
            "steals": self.steals,
            "rejoins": self.rejoins,
            "drains": self.drains,
            "in_transit": in_transit,
            "balanced": (rehomed_shed == adopted + in_transit
                         and admitted
                         == resolved + shed_real + pending + in_transit
                         and self.submitted == admitted + door),
        }
