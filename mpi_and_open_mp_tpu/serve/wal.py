"""Write-ahead ticket journal: durability at *arbitrary* crash points.

The drain checkpoint (PR 7, ``utils.checkpoint.save_state``) survives
only *cooperative* preemption — SIGTERM lands as a flag, the in-flight
batch completes, the pending queue snapshots, the process exits 75. A
``kill -9``, an OOM kill, or node loss never runs that code: every
ticket admitted since the last drain would vanish, which is exactly the
failure a PBS-style requeue loop (the reference's cluster workflow)
actually produces. This module closes that gap with a classic
write-ahead log: every ticket transition is appended — and, per policy,
fsynced — *before* the daemon acts on it, so the admitted set is
reconstructible from disk no matter which instruction the process died
on.

File format (``momp-serve-wal/1``)::

    momp-serve-wal/1\\n                      # ASCII magic line
    [frame]*                                # append-only record frames

    frame := >I payload-length | >I CRC32(payload) | payload
    payload := pickle((rtype, dict))        # one record

Record types and what :func:`replay` does with them:

``ADMIT {id, board, steps, wall, queued_s[, session][, workload]}``
    Ticket enters the pending set. ``wall`` is ``time.time()`` at the
    append (monotonic clocks don't survive a process boundary; wall time
    lets the resuming process carry true queued seconds forward).
    ``session`` is the optional fleet affinity key — the router re-homes
    a dead worker's pending set by consistent-hashing it, so the key
    must survive the journal round trip (absent in pre-fleet journals;
    replay surfaces ``None``). ``workload`` names the stencil rule
    (absent in pre-stencil journals; replay surfaces ``"life"`` — which
    is exactly what those journals ran).
``DISPATCH {ids}``
    A chunk went to the engines. Pending membership is unchanged — a
    ``DISPATCH`` without a later ``RESOLVE``/``SHED`` covering its ids
    means the process died mid-batch, and because dispatch is *pure*
    (same boards + steps → same result, no external side effects) the
    resumed daemon simply re-runs it. Replay reports these ids as
    ``in_flight`` for the accounting line.
``RESOLVE {ids, engine}`` / ``SHED {ids, reason}``
    Tickets leave the pending set (terminal). Results are deliberately
    NOT journaled: the WAL's contract is the *pending set*, not the
    response cache — a resolved ticket's answer either reached its
    caller or is reproducible by redispatch.
``COMPACT {generation, count}``
    Head frame of a rotated journal: the full pending set lives in the
    crash-atomic ``save_state`` snapshot at ``<path>.snap.<generation>``
    and the frames after this one are the tail written since rotation.
    Pool sessions rotate with it: the snapshot's ``pool`` list carries
    ``{id, board, steps, wall}`` per live session (the create board
    plus the *total* journaled step count), so a rotated journal
    re-materializes the pool exactly as a never-rotated one would.

Handle-lifecycle records (the device-resident session pool, PR 12).
These journal *state transitions of resident sessions* rather than
tickets — resident step traffic writes exactly one frame per request
(no ADMIT/DISPATCH/RESOLVE triple), which is what makes the WAL cheap
enough to sit on the handle fast path:

``CREATE {id, board, steps:0, wall}``
    A session entered the pool with this board. The board crosses the
    wire (and the journal) exactly once, here. Re-creating an id that
    is live is an inconsistency error; re-creating after an ``EVICT``
    is a legitimate new lifetime.
``STEP {id, steps}``
    The session advanced ``steps`` generations in place. Write-ahead
    and *authoritative*: resume state is the create board advanced by
    the sum of journaled steps, so a journaled-but-unacked step is
    applied on resume (at-least-once on unacked work, zero acked loss
    — the ack only returns after the frame is durable).
``SNAPSHOT {id, steps_applied}``
    The caller read the session's board. Nothing to replay — the frame
    exists so the crash matrix can kill between a snapshot and the
    next transition and prove the books still balance.
``EVICT {id}``
    The session left the pool (terminal for this lifetime).

**Torn-tail tolerance.** A crash mid-append (SIGKILL between the two
``write``s, a filled disk, the injected ``crash=mid-frame:<k>`` chaos
fault) leaves a torn final frame. :func:`replay` stops at the first
frame that fails its length or CRC check and recovers the clean prefix
— the same discipline as ``utils.checkpoint.restore_state``, applied
per record instead of per file. A torn frame can only be a record whose
append never *returned*, so no acked transition is ever inside the torn
region (the fsync-ladder table below makes that precise).

**The fsync ladder** (``fsync=`` policy) trades durability for append
latency; the loss bound is what the crash-matrix test proves at every
instrumented crash site:

================  ==========================================  =========================
policy            behaviour per append                          loss bound on hard kill
================  ==========================================  =========================
``every-record``  write + flush + fsync                        zero acked records
``every-chunk``   buffer in-process; write+flush+fsync at      ≤ one chunk
                  chunk-lifecycle records (DISPATCH/RESOLVE/    (< ``chunk_records``
                  SHED/COMPACT) or every ``chunk_records``      buffered ADMITs)
                  buffered records, whichever first
``off``           write + flush (OS-buffered, never fsync)     zero on process death;
                                                               unbounded on power cut
================  ==========================================  =========================

``every-chunk`` buffers frames in *user space* — not just skipping the
fsync — so the bound is honest under SIGKILL too (a flushed-but-not-
fsynced record survives process death in the page cache; only the
power-cut story would differ, and that cannot be rehearsed in CI).

**Compaction.** The journal grows with traffic, not with queue depth;
:meth:`TicketWAL.compact` rotates it once ``bytes_since_compact``
crosses the threshold: (1) the pending set goes to
``<path>.snap.<generation>`` through the existing crash-atomic
``save_state`` (tmp sibling + fsync + ``os.replace`` + directory
fsync), (2) a fresh journal containing only the ``COMPACT`` head frame
replaces the old one with the same tmp/replace/dir-fsync discipline,
(3) the superseded snapshot is unlinked. A crash between (1) and (2)
leaves the OLD self-contained journal authoritative (the orphan
snapshot's generation is referenced by no ``COMPACT`` head and is
overwritten by the next rotation); a crash after (2) is the new
journal, complete. No interleaving exposes a state that replays wrong.
"""

from __future__ import annotations

import dataclasses
import os
import pickle
import struct
import time
import zlib

import numpy as np

from mpi_and_open_mp_tpu.robust import chaos
from mpi_and_open_mp_tpu.utils import checkpoint as checkpoint_mod

WAL_MAGIC = b"momp-serve-wal/1\n"
WAL_SNAP_SCHEMA = "momp-serve-wal-snap/1"

_FRAME = struct.Struct(">II")  # payload length, CRC32(payload)
#: Ceiling on a single frame's payload — anything larger in a length
#: field is corruption, not data (the biggest real record is one ADMIT
#: board; bench boards are KBs).
MAX_FRAME_BYTES = 64 << 20

FSYNC_POLICIES = ("every-record", "every-chunk", "off")

#: Record types whose append closes a chunk lifecycle step — the
#: ``every-chunk`` policy syncs on these (and on a full buffer) so a
#: dispatched batch is never less durable than its admits.
_CHUNK_BOUNDARY = ("DISPATCH", "RESOLVE", "SHED", "COMPACT",
                   "CREATE", "STEP", "EVICT")


def _snap_path(path: str, generation: int) -> str:
    return f"{path}.snap.{generation}"


@dataclasses.dataclass
class WALReplay:
    """What :func:`replay` reconstructed from a journal.

    ``pending`` holds admit-ordered entries ``{id, board, steps, wall,
    queued_s}`` — every admitted ticket with no terminal record,
    including the ``in_flight_ids`` of an open ``DISPATCH`` (redispatch
    is idempotent, so they simply rejoin the queue). ``resolved_ids`` /
    ``shed_ids`` close the books: every id the dead process journaled
    terminal. ``shed_reasons`` splits the shed set per policy reason —
    a membership audit needs to tell a ``re-homed`` handoff (which must
    pair with an adoption on some OTHER worker's journal) from a real
    terminal shed. ``pool_sessions`` maps live session id → ``{id,
    board, steps, wall}`` — the create board plus the summed journaled
    step count, which *is* the session's resumable state (re-materialize
    by advancing ``board`` ``steps`` generations). ``truncated_at`` is
    the byte offset of a torn tail (``None`` for a clean EOF).
    """

    pending: list[dict]
    in_flight_ids: set[int]
    resolved_ids: set[int]
    shed_ids: set[int]
    shed_reasons: dict[str, set[int]] = dataclasses.field(
        default_factory=dict)
    pool_sessions: dict[str, dict] = dataclasses.field(default_factory=dict)
    generation: int = 0
    frames: int = 0
    truncated_at: int | None = None

    @property
    def truncated(self) -> bool:
        return self.truncated_at is not None

    def counts(self) -> dict:
        """The accounting sub-object the resume CLI line publishes."""
        return {
            "pending": len(self.pending),
            "in_flight": len(self.in_flight_ids),
            "resolved": len(self.resolved_ids),
            "shed": len(self.shed_ids),
            "pool_sessions": len(self.pool_sessions),
            "generation": self.generation,
            "frames": self.frames,
            "truncated": self.truncated,
        }


def _encode(rtype: str, payload: dict) -> bytes:
    blob = pickle.dumps((rtype, payload), protocol=pickle.HIGHEST_PROTOCOL)
    return _FRAME.pack(len(blob), zlib.crc32(blob)) + blob


def replay(path: str | os.PathLike) -> WALReplay:
    """Reconstruct the exact pending set (plus any in-flight batch) from
    a journal, tolerating a torn tail.

    Raises ``ValueError`` only when the file cannot be a journal at all
    (missing, bad magic) or its ``COMPACT`` head references a snapshot
    that is missing/corrupt/mismatched — the cases where *no* safe
    reconstruction exists and the resume ladder must fall to the drain
    checkpoint. A torn or corrupt tail is NOT an error: replay stops at
    the first bad frame and returns the clean prefix.
    """
    from mpi_and_open_mp_tpu.obs import metrics, trace

    path = os.path.abspath(os.fspath(path))
    try:
        with open(path, "rb") as fd:
            blob = fd.read()
    except OSError as e:
        raise ValueError(
            f"no readable ticket journal at {path} "
            f"({type(e).__name__}: {e})") from e
    if not blob.startswith(WAL_MAGIC):
        raise ValueError(
            f"ticket journal at {path} has a bad magic header — not a "
            "momp-serve-wal/1 file (or corrupted at offset 0)")

    pending: dict[int, dict] = {}
    rep = WALReplay(pending=[], in_flight_ids=set(),
                    resolved_ids=set(), shed_ids=set())
    off = len(WAL_MAGIC)
    while off < len(blob):
        if len(blob) - off < _FRAME.size:
            rep.truncated_at = off
            break
        length, want_crc = _FRAME.unpack_from(blob, off)
        body = off + _FRAME.size
        if length > MAX_FRAME_BYTES or body + length > len(blob):
            rep.truncated_at = off
            break
        payload = blob[body:body + length]
        if zlib.crc32(payload) != want_crc:
            rep.truncated_at = off
            break
        try:
            rtype, rec = pickle.loads(payload)
        except Exception:  # noqa: BLE001 — CRC passed but undecodable
            rep.truncated_at = off
            break
        if rtype == "ADMIT":
            tid = int(rec["id"])
            if tid in pending or tid in rep.resolved_ids | rep.shed_ids:
                raise ValueError(
                    f"ticket journal at {path} re-admits ticket {tid} "
                    f"at frame {rep.frames} — the journal is internally "
                    "inconsistent, refusing to guess a pending set")
            pending[tid] = {
                "id": tid, "board": np.asarray(rec["board"]),
                "steps": int(rec["steps"]),
                "wall": float(rec.get("wall", 0.0)),
                "queued_s": float(rec.get("queued_s", 0.0)),
                "session": rec.get("session"),
                # Pre-stencil journals carry no workload: life, exactly.
                "workload": str(rec.get("workload", "life")),
            }
        elif rtype == "DISPATCH":
            for tid in rec["ids"]:
                if tid in pending:
                    rep.in_flight_ids.add(int(tid))
        elif rtype == "RESOLVE":
            for tid in rec["ids"]:
                pending.pop(int(tid), None)
                rep.in_flight_ids.discard(int(tid))
                rep.resolved_ids.add(int(tid))
        elif rtype == "SHED":
            reason = str(rec.get("reason", ""))
            for tid in rec["ids"]:
                pending.pop(int(tid), None)
                rep.in_flight_ids.discard(int(tid))
                rep.shed_ids.add(int(tid))
                rep.shed_reasons.setdefault(reason, set()).add(int(tid))
        elif rtype == "CREATE":
            sid = str(rec["id"])
            if sid in rep.pool_sessions:
                raise ValueError(
                    f"ticket journal at {path} re-creates live pool "
                    f"session {sid!r} at frame {rep.frames} — the "
                    "journal is internally inconsistent")
            rep.pool_sessions[sid] = {
                "id": sid, "board": np.asarray(rec["board"]),
                "steps": int(rec.get("steps", 0)),
                "wall": float(rec.get("wall", 0.0)),
            }
        elif rtype == "STEP":
            sid = str(rec["id"])
            if sid not in rep.pool_sessions:
                raise ValueError(
                    f"ticket journal at {path} steps unknown pool "
                    f"session {sid!r} at frame {rep.frames}")
            rep.pool_sessions[sid]["steps"] += int(rec["steps"])
        elif rtype == "SNAPSHOT":
            sid = str(rec["id"])
            if sid not in rep.pool_sessions:
                raise ValueError(
                    f"ticket journal at {path} snapshots unknown pool "
                    f"session {sid!r} at frame {rep.frames}")
            # Nothing to mutate: a snapshot is a read. The frame exists
            # so the crash matrix can land between it and the next
            # transition and prove the replayed state is unaffected.
        elif rtype == "EVICT":
            sid = str(rec["id"])
            if sid not in rep.pool_sessions:
                raise ValueError(
                    f"ticket journal at {path} evicts unknown pool "
                    f"session {sid!r} at frame {rep.frames}")
            del rep.pool_sessions[sid]
        elif rtype == "COMPACT":
            if rep.frames != 0:
                raise ValueError(
                    f"ticket journal at {path} carries a COMPACT record "
                    f"at frame {rep.frames}; a rotated journal starts "
                    "with it — the file is inconsistent")
            gen = int(rec["generation"])
            try:
                snap = checkpoint_mod.restore_state(_snap_path(path, gen))
            except ValueError as e:
                raise ValueError(
                    f"ticket journal at {path} references compaction "
                    f"snapshot generation {gen} but the snapshot is "
                    f"unreadable ({e})"[:400]) from e
            if (not isinstance(snap, dict)
                    or snap.get("schema") != WAL_SNAP_SCHEMA
                    or int(snap.get("generation", -1)) != gen):
                raise ValueError(
                    f"ticket journal at {path} references compaction "
                    f"snapshot generation {gen} but "
                    f"{_snap_path(path, gen)} does not match it")
            rep.generation = gen
            for entry in snap["pending"]:
                pending[int(entry["id"])] = {
                    "id": int(entry["id"]),
                    "board": np.asarray(entry["board"]),
                    "steps": int(entry["steps"]),
                    "wall": float(entry.get("wall", 0.0)),
                    "queued_s": float(entry.get("queued_s", 0.0)),
                    "session": entry.get("session"),
                    "workload": str(entry.get("workload", "life")),
                }
            for entry in snap.get("pool", []):
                sid = str(entry["id"])
                rep.pool_sessions[sid] = {
                    "id": sid, "board": np.asarray(entry["board"]),
                    "steps": int(entry["steps"]),
                    "wall": float(entry.get("wall", 0.0)),
                }
        else:
            raise ValueError(
                f"ticket journal at {path} carries unknown record type "
                f"{rtype!r} at frame {rep.frames}")
        rep.frames += 1
        off = body + length

    rep.pending = list(pending.values())
    metrics.inc("serve.wal.replays")
    trace.event("serve.wal.replay", path=path, **rep.counts())
    return rep


class TicketWAL:
    """The append side of the journal — one instance per daemon.

    ``chunk_records`` bounds the ``every-chunk`` buffer (the daemon
    passes its ``max_batch``, making "≤ one chunk" literal);
    ``compact_bytes`` is the rotation threshold the daemon polls via
    :meth:`should_compact`. Opening an existing journal appends to it;
    the daemon's resume path rotates immediately instead, so a live
    journal is always internally consistent with the writing process's
    ticket ids.
    """

    def __init__(self, path: str | os.PathLike, *,
                 fsync: str = "every-record", chunk_records: int = 8,
                 compact_bytes: int = 1 << 20):
        if fsync not in FSYNC_POLICIES:
            raise ValueError(
                f"unknown WAL fsync policy {fsync!r} "
                f"(want one of {FSYNC_POLICIES})")
        if chunk_records < 1:
            raise ValueError(
                f"chunk_records must be >= 1, got {chunk_records}")
        self.path = os.path.abspath(os.fspath(path))
        self.fsync = fsync
        self.chunk_records = int(chunk_records)
        self.compact_bytes = int(compact_bytes)
        self._generation = 0
        self._buf: list[bytes] = []
        self._bytes_since_compact = 0
        self.stats_records = 0
        self.stats_bytes = 0
        self.stats_syncs = 0
        self.stats_sync_seconds = 0.0
        self.stats_compactions = 0
        outdir = os.path.dirname(self.path)
        if outdir:
            os.makedirs(outdir, exist_ok=True)
        fresh = (not os.path.exists(self.path)
                 or os.path.getsize(self.path) == 0)
        self._fd = open(self.path, "ab")
        if fresh:
            self._fd.write(WAL_MAGIC)
            self._fd.flush()
            self._sync_fd()
            checkpoint_mod._fsync_dir(self.path)

    # -- record appends ----------------------------------------------------

    def admit(self, ticket_id: int, board, steps: int, *,
              wall: float | None = None, queued_s: float = 0.0,
              session: str | None = None,
              workload: str = "life") -> None:
        self._append("ADMIT", {
            "id": int(ticket_id), "board": np.asarray(board),
            "steps": int(steps),
            "wall": time.time() if wall is None else float(wall),
            "queued_s": float(queued_s),
            "session": session,
            "workload": str(workload),
        })

    def dispatch_begin(self, ticket_ids: list[int]) -> None:
        self._append("DISPATCH", {"ids": [int(i) for i in ticket_ids]})

    def resolve(self, ticket_ids: list[int], engine: str | None = None) -> None:
        self._append("RESOLVE", {"ids": [int(i) for i in ticket_ids],
                                 "engine": engine})

    def shed(self, ticket_ids: list[int], reason: str) -> None:
        self._append("SHED", {"ids": [int(i) for i in ticket_ids],
                              "reason": str(reason)})

    # -- pool handle-lifecycle appends --------------------------------------

    def pool_create(self, session: str, board, *,
                    wall: float | None = None) -> None:
        self._append("CREATE", {
            "id": str(session), "board": np.asarray(board), "steps": 0,
            "wall": time.time() if wall is None else float(wall),
        })

    def pool_step(self, session: str, steps: int) -> None:
        self._append("STEP", {"id": str(session), "steps": int(steps)})

    def pool_snapshot(self, session: str, steps_applied: int) -> None:
        self._append("SNAPSHOT", {"id": str(session),
                                  "steps_applied": int(steps_applied)})

    def pool_evict(self, session: str) -> None:
        self._append("EVICT", {"id": str(session)})

    # -- compaction --------------------------------------------------------

    def should_compact(self) -> bool:
        return self._bytes_since_compact >= self.compact_bytes

    def compact(self, pending_entries: list[dict],
                pool_sessions: dict[str, dict] | None = None) -> None:
        """Rotate the journal: pending set to a crash-atomic snapshot,
        journal file atomically replaced by a COMPACT-headed fresh one.
        ``pending_entries`` are ``{id, board, steps, wall, queued_s}``
        dicts in admit order (the daemon computes ``queued_s`` against
        its own clock at rotation time). ``pool_sessions`` maps live
        session id → ``{id, board, steps, wall}`` — the create board
        plus total journaled steps, i.e. the same resumable shape
        ``replay`` reconstructs, so the rotation never touches the
        device (no snapshot reads at compact time)."""
        from mpi_and_open_mp_tpu.obs import metrics, trace

        gen = self._generation + 1
        entries = [{
            "id": int(e["id"]), "board": np.asarray(e["board"]),
            "steps": int(e["steps"]), "wall": float(e.get("wall", 0.0)),
            "queued_s": float(e.get("queued_s", 0.0)),
            "session": e.get("session"),
            "workload": str(e.get("workload", "life")),
        } for e in pending_entries]
        pool = [{
            "id": str(s["id"]), "board": np.asarray(s["board"]),
            "steps": int(s["steps"]), "wall": float(s.get("wall", 0.0)),
        } for s in (pool_sessions or {}).values()]
        with trace.span("serve.wal.compact", generation=gen,
                        pending=len(entries), pool=len(pool)):
            checkpoint_mod.save_state(_snap_path(self.path, gen), {
                "schema": WAL_SNAP_SCHEMA, "generation": gen,
                "pending": entries, "pool": pool,
            })
            head = WAL_MAGIC + _encode(
                "COMPACT", {"generation": gen, "count": len(entries)})
            tmp = self.path + ".tmp"
            with open(tmp, "wb") as fd:
                fd.write(head)
                fd.flush()
                os.fsync(fd.fileno())
            self._fd.close()
            os.replace(tmp, self.path)
            checkpoint_mod._fsync_dir(self.path)
            self._fd = open(self.path, "ab")
        # The superseded snapshot is referenced by nothing now; best
        # effort — a leftover file can only waste bytes, never replay.
        try:
            os.unlink(_snap_path(self.path, self._generation))
        except OSError:
            pass
        self._generation = gen
        self._buf.clear()
        self._bytes_since_compact = 0
        self.stats_compactions += 1
        metrics.inc("serve.wal.compactions")

    # -- durability plumbing -----------------------------------------------

    def _append(self, rtype: str, payload: dict) -> None:
        from mpi_and_open_mp_tpu.obs import metrics

        frame = _encode(rtype, payload)
        if chaos.crash_armed("mid-frame"):
            # The injected torn write: half a frame reaches the OS, then
            # the process dies as hard as a SIGKILL would — replay must
            # truncate here and recover the clean prefix.
            self._fd.write(frame[:max(1, len(frame) // 2)])
            self._fd.flush()
            os.fsync(self._fd.fileno())
            chaos.crash_now()
        if self.fsync == "every-chunk":
            self._buf.append(frame)
            if (rtype in _CHUNK_BOUNDARY
                    or len(self._buf) >= self.chunk_records):
                self._flush_buffer(sync=True)
        else:
            self._fd.write(frame)
            self._fd.flush()
            if self.fsync == "every-record":
                self._sync_fd()
        self.stats_records += 1
        self.stats_bytes += len(frame)
        self._bytes_since_compact += len(frame)
        metrics.inc("serve.wal.records", type=rtype)
        metrics.inc("serve.wal.bytes", len(frame))

    def _flush_buffer(self, sync: bool) -> None:
        if self._buf:
            self._fd.write(b"".join(self._buf))
            self._buf.clear()
        self._fd.flush()
        if sync:
            self._sync_fd()

    def _sync_fd(self) -> None:
        from mpi_and_open_mp_tpu.utils.timing import Timer

        with Timer() as t:
            os.fsync(self._fd.fileno())
        self.stats_syncs += 1
        self.stats_sync_seconds += t.elapsed

    def sync(self) -> None:
        """Force buffered records to durable storage regardless of
        policy — the preemption drain and clean shutdown call this so a
        polite exit is never less durable than a crash."""
        self._flush_buffer(sync=True)

    def close(self) -> None:
        self._flush_buffer(sync=self.fsync != "off")
        self._fd.close()

    def stats(self) -> dict:
        """The journal-overhead numbers the bench line publishes."""
        return {
            "fsync": self.fsync,
            "records": self.stats_records,
            "bytes": self.stats_bytes,
            "syncs": self.stats_syncs,
            "sync_seconds": round(self.stats_sync_seconds, 6),
            "compactions": self.stats_compactions,
            "generation": self._generation,
        }
