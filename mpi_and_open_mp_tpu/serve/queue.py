"""Bounded, admission-controlled, checkpointable request queue.

The ticket ledger of the serving daemon: every submission becomes a
:class:`Ticket` that ends in exactly one terminal state — ``DONE`` with
a result and an engine stamp, or ``SHED`` with an explicit reason from
the ``serve.policy`` vocabulary. Nothing is ever silently dropped: a
SIGTERM drain snapshots the pending tickets (payload boards, step
counts, submission order) through the crash-atomic CRC state checkpoint
(``utils.checkpoint.save_state``) and :meth:`ServeQueue.restore` readmits
them unconditionally — admission control applies at the door, not to
requests the daemon already accepted.

Buckets key on ``(shape, dtype, steps, workload)`` — one bucket is one
compiled program's worth of same-shape same-rule work (steps being a
runtime scalar, the split by steps exists because all boards of a stack
advance together, not for compilation; the split by workload exists
because a heat board and a life board of one shape run different
programs). Deadline bookkeeping lives here (oldest pending
ticket per bucket); the policy decides when a bucket is due, the daemon
dispatches it.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from mpi_and_open_mp_tpu.serve import policy as policy_mod
from mpi_and_open_mp_tpu.serve.policy import ServePolicy

PENDING = "pending"
DONE = "done"
SHED = "shed"

STATE_SCHEMA = "momp-serve-queue/1"


@dataclasses.dataclass
class Ticket:
    """One request's life, admission through terminal state."""

    id: int
    #: The payload for ship-every-ticket requests; ``None`` for a
    #: resident session step — the board never leaves the device, the
    #: ticket carries only the pool handle.
    board: np.ndarray | None
    steps: int
    submitted_at: float
    state: str = PENDING
    result: np.ndarray | None = None
    reason: str | None = None  # shed reason (policy.SHED_*)
    engine: str | None = None  # provenance stamp of the resolving dispatch
    resolved_at: float | None = None
    resumed: bool = False  # restored from a drain checkpoint
    #: Fleet affinity key: requests sharing a ``session`` route to the
    #: same worker (consistent hash in ``serve.router``). ``None`` for
    #: single-daemon use — affinity then falls back to a per-ticket key.
    session: str | None = None
    #: Seconds this request already spent queued in PREVIOUS processes.
    #: ``submitted_at`` is re-stamped against the resuming clock
    #: (monotonic timestamps don't cross a process boundary), so without
    #: this carry a resumed ticket's latency would silently forget its
    #: pre-crash queue time and post-resume p99 would flatter the tail.
    queued_before_s: float = 0.0
    #: Device-resident handle (``serve.pool.Handle``) for a session step
    #: ticket. Set iff ``board`` is ``None``.
    handle: object | None = None
    #: Stencil workload name (``stencils.get``): which rule advances this
    #: board. Part of the bucket key — a heat board and a life board of
    #: the same shape must never share a dispatch.
    workload: str = "life"

    @property
    def bucket_key(self) -> tuple:
        if self.handle is not None:
            # Resident steps bucket by slab: every lane of a slab is
            # advanced by the SAME donated dispatch, so slab-mates with
            # equal step counts coalesce into one program invocation.
            return ("pool", self.handle.slab, self.steps)
        return (self.board.shape, self.board.dtype.str, self.steps,
                self.workload)

    @property
    def latency_s(self) -> float | None:
        """True end-to-end seconds, first submission to terminal state,
        across every process that held the ticket (``None`` while
        pending)."""
        if self.resolved_at is None:
            return None
        return self.resolved_at - self.submitted_at + self.queued_before_s


class ServeQueue:
    """Ticket store + admission gate. All times come from the caller
    (``now`` arguments) so tests drive deadlines with a fake clock."""

    def __init__(self, policy: ServePolicy | None = None):
        self.policy = policy or ServePolicy()
        self._tickets: dict[int, Ticket] = {}
        self._next_ticket = 0

    # -- intake ------------------------------------------------------------

    def submit(self, board: np.ndarray, steps: int, now: float,
               session: str | None = None,
               workload: str = "life") -> Ticket:
        """Admit or reject one request; ALWAYS returns a ticket. A
        rejected ticket is already terminal (``SHED`` with the admission
        reason) so callers account for every submission the same way.
        ``workload`` names the stencil rule (``stencils.get``); the
        board must match the spec's layout — 2D, or channels-leading 3D
        for multi-channel rules like gray_scott."""
        from mpi_and_open_mp_tpu import stencils
        from mpi_and_open_mp_tpu.obs import metrics, trace

        try:
            spec = stencils.get(workload)
        except KeyError as e:
            raise ValueError(str(e)) from None
        board = np.asarray(board)
        if (board.ndim < 2
                or board.shape != spec.board_shape(*board.shape[-2:])):
            want = ("3D (channels, ny, nx)" if spec.channels > 1
                    else "2D (ny, nx)")
            raise ValueError(
                f"submit: workload {workload!r} wants one {want} board "
                f"per request, got shape {board.shape}")
        steps = int(steps)
        if steps < 0:
            raise ValueError(f"submit: steps must be >= 0, got {steps}")
        t = Ticket(self._next_ticket, board, steps, float(now),
                   session=session, workload=str(workload))
        self._next_ticket += 1
        counts = self._bucket_counts()
        counts[t.bucket_key] = counts.get(t.bucket_key, 0) + 1
        reason = policy_mod.admit(
            self.policy, self.depth(),
            [(n, self._slice_width(key)) for key, n in counts.items()
             if key[0] != "pool"])
        self._tickets[t.id] = t
        metrics.inc("serve.requests")
        if reason is not None:
            self._shed(t, reason, now)
        else:
            metrics.inc("serve.admitted")
            trace.event("serve.admit", ticket=t.id,
                        shape=f"{board.shape[-2]}x{board.shape[-1]}",
                        steps=steps, workload=t.workload)
        return t

    def submit_session(self, session: str, handle, steps: int,
                       now: float) -> Ticket:
        """Admit or reject one resident session step. The padding-waste
        gate does not apply — a pool dispatch advances whole planes in
        place, so a partly-live slab costs exactly what a full one does
        and there is no dead-padding denominator to project. Depth still
        gates (pending handles queue host bookkeeping and dispatch
        latency like any ticket)."""
        from mpi_and_open_mp_tpu.obs import metrics, trace

        steps = int(steps)
        if steps < 0:
            raise ValueError(
                f"submit_session: steps must be >= 0, got {steps}")
        t = Ticket(self._next_ticket, None, steps, float(now),
                   session=str(session), handle=handle)
        self._next_ticket += 1
        metrics.inc("serve.requests")
        if self.depth() >= self.policy.max_depth:
            self._tickets[t.id] = t
            self._shed(t, policy_mod.SHED_DEPTH, now)
            return t
        self._tickets[t.id] = t
        metrics.inc("serve.admitted")
        trace.event("serve.admit", ticket=t.id, session=str(session),
                    steps=steps, resident=True)
        return t

    def restore_ticket(self, board: np.ndarray, steps: int,
                       now: float, queued_s: float = 0.0,
                       session: str | None = None,
                       workload: str = "life") -> Ticket:
        """Re-admit one drained ticket from a checkpoint — NO admission
        gate (it was already admitted once; dropping it now would break
        the never-lose-a-ticket contract). The deadline clock restarts at
        ``now``: monotonic timestamps don't survive a process boundary,
        so the seconds already spent queued arrive as ``queued_s`` and
        keep accruing into :attr:`Ticket.latency_s`."""
        from mpi_and_open_mp_tpu.obs import metrics

        t = Ticket(self._next_ticket, np.asarray(board), int(steps),
                   float(now), resumed=True, session=session,
                   queued_before_s=float(queued_s),
                   workload=str(workload))
        self._next_ticket += 1
        self._tickets[t.id] = t
        metrics.inc("serve.requests")
        metrics.inc("serve.admitted")
        metrics.inc("serve.resumed_tickets")
        return t

    # -- queries -----------------------------------------------------------

    def depth(self) -> int:
        return sum(1 for t in self._tickets.values() if t.state == PENDING)

    def pending(self) -> list[Ticket]:
        """Pending tickets in submission order (dict preserves it)."""
        return [t for t in self._tickets.values() if t.state == PENDING]

    def tickets(self) -> list[Ticket]:
        """Every ticket ever submitted, in submission order."""
        return list(self._tickets.values())

    def _bucket_counts(self) -> dict[tuple, int]:
        counts: dict[tuple, int] = {}
        for t in self.pending():
            counts[t.bucket_key] = counts.get(t.bucket_key, 0) + 1
        return counts

    def _slice_width(self, bucket_key: tuple) -> int | None:
        """The pad width the dispatcher will round this bucket with
        (``ops.pallas_life.batch_slice_width``) so admission's
        padding-waste projection matches the actual dispatch. Cached per
        shape — the gate is pure arithmetic on (ny, nx) plus one env
        flag, both stable for the process lifetime. Non-life buckets
        dispatch the generic stencil engine (no slice-width rounding)."""
        if bucket_key[-1] != "life":
            return None
        shape = bucket_key[0]
        try:
            return self._width_cache[shape]
        except AttributeError:
            self._width_cache: dict[tuple, int | None] = {}
        except KeyError:
            pass
        import jax

        from mpi_and_open_mp_tpu.ops import pallas_life

        width = pallas_life.batch_slice_width(
            shape, on_tpu=jax.default_backend() == "tpu")
        self._width_cache[shape] = width
        return width

    def buckets(self) -> dict[tuple, list[Ticket]]:
        """Pending tickets grouped by bucket, submission order inside."""
        out: dict[tuple, list[Ticket]] = {}
        for t in self.pending():
            out.setdefault(t.bucket_key, []).append(t)
        return out

    def due_chunks(self, now: float, drain: bool = False) -> list[list[Ticket]]:
        """Dispatchable chunks: every full ``max_batch`` slice of every
        bucket, plus the remainder of any bucket whose oldest pending
        ticket has waited ``max_wait_s`` (or everything when draining).
        Chunks come out in oldest-ticket-first order so a starved bucket
        is served before a fresh full one."""
        chunks: list[list[Ticket]] = []
        for key, group in self.buckets().items():
            # A pool bucket's natural chunk is the slab's lane count:
            # one donated dispatch advances every lane of one plane, so
            # there is no reason to split below — or batch above — 32.
            mb = 32 if key[0] == "pool" else self.policy.max_batch
            due = drain or (now - group[0].submitted_at
                            >= self.policy.max_wait_s)
            lo = 0
            while len(group) - lo >= mb:
                chunks.append(group[lo:lo + mb])
                lo += mb
            if due and lo < len(group):
                chunks.append(group[lo:])
        chunks.sort(key=lambda c: c[0].id)
        return chunks

    def next_deadline(self) -> float | None:
        """The earliest instant any bucket becomes due, or ``None`` when
        nothing is pending — the daemon's idle-sleep horizon."""
        oldest = [g[0].submitted_at for g in self.buckets().values()]
        if not oldest:
            return None
        return min(oldest) + self.policy.max_wait_s

    # -- terminal transitions ---------------------------------------------

    def resolve(self, ticket: Ticket, result: np.ndarray, engine: str,
                now: float) -> None:
        from mpi_and_open_mp_tpu.obs import metrics

        ticket.state = DONE
        ticket.result = result
        ticket.engine = engine
        ticket.resolved_at = float(now)
        metrics.inc("serve.resolved")
        metrics.observe("serve.latency_seconds", ticket.latency_s)

    def shed_ticket(self, ticket: Ticket, reason: str, now: float) -> None:
        self._shed(ticket, reason, now)

    def _shed(self, ticket: Ticket, reason: str, now: float) -> None:
        from mpi_and_open_mp_tpu.obs import metrics, trace

        if reason not in policy_mod.SHED_REASONS:
            raise ValueError(f"unknown shed reason {reason!r} "
                             f"(want one of {policy_mod.SHED_REASONS})")
        ticket.state = SHED
        ticket.reason = reason
        ticket.resolved_at = float(now)
        metrics.inc("serve.shed", reason=reason)
        trace.event("serve.shed", ticket=ticket.id, reason=reason)

    # -- checkpoint round trip --------------------------------------------

    def snapshot(self, now: float | None = None) -> dict:
        """The pending set as a picklable tree for
        ``utils.checkpoint.save_state`` — ticket order, payloads, step
        counts, the original ids (provenance: an operator can map a
        resumed ticket back to the pre-preemption submission), and each
        ticket's cumulative queued seconds as of ``now`` (pass the
        drain clock so a resumed ticket's latency keeps counting from
        its FIRST submission, not the restore). Resident session
        tickets (``board is None``) are EXCLUDED: their durable state is
        the WAL's handle-lifecycle frames, not the queue — restoring
        one here would double-apply its step on resume."""
        return {
            "schema": STATE_SCHEMA,
            "next_ticket": self._next_ticket,
            "pending": [
                {"id": t.id, "board": np.asarray(t.board), "steps": t.steps,
                 "session": t.session, "workload": t.workload,
                 "queued_s": (t.queued_before_s
                              + (float(now) - t.submitted_at
                                 if now is not None else 0.0))}
                for t in self.pending() if t.board is not None
            ],
        }

    def restore(self, state: dict, now: float) -> list[Ticket]:
        """Re-admit every pending ticket of a :meth:`snapshot` tree, in
        its original order. Raises ``ValueError`` on a tree that isn't a
        serve-queue snapshot (wrong schema / missing fields)."""
        if not isinstance(state, dict) or state.get("schema") != STATE_SCHEMA:
            raise ValueError(
                "not a serve-queue checkpoint: schema is "
                f"{state.get('schema') if isinstance(state, dict) else type(state)!r},"
                f" want {STATE_SCHEMA!r}")
        pending = state.get("pending")
        if not isinstance(pending, list):
            raise ValueError(
                "serve-queue checkpoint is missing its pending list")
        out = []
        for item in pending:
            try:
                board, steps = item["board"], item["steps"]
            except (TypeError, KeyError) as e:
                raise ValueError(
                    f"serve-queue checkpoint entry is malformed: {item!r}"
                ) from e
            out.append(self.restore_ticket(
                board, steps, now,
                queued_s=float(item.get("queued_s", 0.0)),
                session=item.get("session"),
                workload=str(item.get("workload", "life"))))
        return out
