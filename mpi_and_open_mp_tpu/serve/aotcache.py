"""Durable AOT executable cache: millisecond warm resume for the daemon.

PR 7/8 made the serving daemon's *state* survive anything (drain
checkpoint, write-ahead ticket journal); this module does the same for
its *programs*. The daemon's compiled-program set is small and closed —
one program per (board shape, dtype) x power-of-two batch bucket, at
most ``log2(max_batch)+1`` buckets per shape, with the step count a
runtime scalar on every engine path — so the whole set serializes
through ``jax.export`` into a handful of on-disk artifacts, and a
requeued/resumed daemon deserializes them in milliseconds instead of
re-tracing its first batch into the restored tickets' p99. This is the
compilation analogue of PAPERS.md's persistent MPI requests: plan and
compile once, persist the fixed schedule, reuse it across every
restart. The proof instrument is the ``jit.retrace{fn=life_batch_*}``
counter set: a deserialized program's ``Exported.call`` never re-runs
the traced Python bodies, so a warm resume shows ZERO retraces.

**Keying: a fingerprint, not a filename convention.** Every artifact is
keyed by the full fingerprint of what made the program: stack shape,
dtype, the steps signature (runtime int32 scalar), batch bucket, the
engine path ``native_path_batch`` would pick, the stencil workload the
program advances, jax/jaxlib versions, platform/device kind/topology,
and a content hash of the engine source files (``ops/bitlife.py`` +
``ops/pallas_life.py`` + the ``stencils`` spec/engine the life step is
generated from). The digest of that
fingerprint is the filename; the fingerprint itself is stored INSIDE
the envelope and re-verified on load, so a stale artifact (upgraded
jax, edited kernels, different chip) can never be executed — it is
*key-stale*, quarantined, and rebuilt.

**Hardened like the WAL, not like a cache.** Artifacts use the repo's
crash-atomic envelope discipline (``MOMP-AOT/1`` magic + ``>QI``
length/CRC32 header + payload, written tmp+fsync+``os.replace``+parent
dir fsync — the exact ``utils/checkpoint.py`` frame). A corrupt,
truncated, or key-stale artifact is quarantined to a
generation-stamped ``.corrupt.*``/``.stale.*`` sibling
(:func:`utils.checkpoint.quarantine` — forensics preserved, never
clobbered) and the daemon falls back to a fresh trace with
``aot:miss``/``aot:corrupt`` provenance; every deserialized executable
is additionally oracle parity-gated on its first use, so even a
CRC-valid artifact that computes wrong answers is caught, quarantined,
and recovered from through the guards ladder. A bad cache can never
crash or wrong-answer the daemon. ``MOMP_CHAOS aot_corrupt=<kind>:<k>``
(kinds: ``bitflip``, ``skew``) corrupts artifacts at save time so both
failure modes are drilled deterministically, in-process and in the CI
``serve-warm-resume`` job.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import struct
import time
import zlib

import numpy as np

from mpi_and_open_mp_tpu.utils import checkpoint as checkpoint_mod

AOT_MAGIC = b"MOMP-AOT/1\n"
_HEADER = struct.Struct(">QI")  # payload length, CRC32

#: The steps calling convention every cached program shares: one int32
#: runtime scalar, so one program per stack shape serves any step count.
STEPS_SIGNATURE = "runtime-scalar-int32"

_CODE_FP = None


class ArtifactError(ValueError):
    """A cache artifact that must not be executed. ``kind`` is the
    provenance bucket: ``"corrupt"`` (bad magic/length/CRC/undecodable
    payload/undeserializable blob) or ``"stale"`` (intact envelope whose
    stored fingerprint doesn't match this process — version skew, edited
    kernels, different silicon)."""

    def __init__(self, kind: str, message: str):
        super().__init__(message)
        self.kind = kind


class ParityError(RuntimeError):
    """A deserialized executable whose first result diverged from the
    NumPy oracle — raised from the dispatch rung so the guards ladder
    recovers through a fresh trace."""


def code_fingerprint() -> str:
    """Content hash of the engine sources the cached programs compile
    from. Editing a kernel invalidates every artifact it produced —
    correctness beats cache hits."""
    global _CODE_FP
    if _CODE_FP is None:
        from mpi_and_open_mp_tpu.ops import bitlife, pallas_life
        from mpi_and_open_mp_tpu.stencils import engine as stencil_engine
        from mpi_and_open_mp_tpu.stencils import spec as stencil_spec

        h = hashlib.sha256()
        # The stencil engine/spec sources are part of the hash because
        # the life padded step is GENERATED from them now — editing the
        # generic engine can change the compiled life program.
        for mod in (bitlife, pallas_life, stencil_engine, stencil_spec):
            with open(mod.__file__, "rb") as fd:
                h.update(fd.read())
        _CODE_FP = h.hexdigest()[:16]
    return _CODE_FP


def fingerprint(stack_shape: tuple[int, int, int], dtype, *,
                program: str = "bucket", donated: bool = False,
                workload: str = "life") -> dict:
    """The full cache key for one compiled program — everything that can
    change the executable or its validity. ``program`` names which
    program family the key identifies (``"bucket"`` for the daemon's
    padded batch programs, ``"pool-step"`` for the session pool's
    donated in-place step); ``donated`` is keyed because input aliasing
    changes the executable's buffer contract even at identical shapes.
    Donation does not survive ``jax.export``, so pool-step keys are
    identity stamps for the in-process jit cache, never load targets.
    ``workload`` is the stencil rule the program advances — keyed so a
    life artifact can never serve a heat bucket of the same shape (only
    life programs are cached today; the field future-proofs the key)."""
    import jax
    import jaxlib

    from mpi_and_open_mp_tpu.ops import pallas_life

    b, ny, nx = (int(x) for x in stack_shape)
    on_tpu = jax.default_backend() == "tpu"
    try:
        device_kind = jax.devices()[0].device_kind
    except Exception:  # noqa: BLE001 — keying must not hang/crash
        device_kind = "unknown"
    return {
        "schema": "momp-aot/1",
        "shape": [ny, nx],
        "dtype": str(np.dtype(dtype)),
        "bucket": b,
        "program": str(program),
        "donated": bool(donated),
        "workload": str(workload),
        "steps": STEPS_SIGNATURE,
        "engine_path": "batch:" + pallas_life.native_path_batch(
            (b, ny, nx), on_tpu=on_tpu),
        # Keyed explicitly as well as via engine_path: a cell-packed
        # artifact must never serve a bitsliced bucket (different pack
        # transpose), even if path names are ever renamed.
        "pack_layout": pallas_life.batch_pack_layout(
            (b, ny, nx), on_tpu=on_tpu),
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": jax.default_backend(),
        "device_kind": device_kind,
        "topology": f"{jax.default_backend()}:{jax.device_count()}",
        "code": code_fingerprint(),
    }


def digest_for(key: dict) -> str:
    blob = json.dumps(key, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:32]


def bucket_sizes(max_batch: int) -> list[int]:
    """Every batch size ``serve.batcher.bucket_batch_size`` can emit:
    powers of two below ``max_batch`` plus ``max_batch`` itself, plus —
    for bitsliced-eligible shapes — the 32-board plane multiples the
    slice-width rounding pads to. Still O(log + max_batch/32) programs
    per shape."""
    sizes, b = set(), 1
    while b < max_batch:
        sizes.add(b)
        b *= 2
    sizes.add(int(max_batch))
    w = 32
    while w <= max_batch:
        sizes.add(w)
        w += 32
    return sorted(sizes)


def save_artifact(path: str, key: dict, blob: bytes) -> None:
    """Write one serialized executable crash-atomically (the
    ``utils.checkpoint`` envelope + tmp/fsync/replace/dir-fsync dance).
    An armed ``MOMP_CHAOS aot_corrupt=`` plan then damages the artifact
    ON DISK, after the clean write — the in-memory program this process
    already holds stays good, so the fault surfaces exactly where a real
    bit rot would: in the NEXT process's load."""
    from mpi_and_open_mp_tpu.robust import chaos

    payload = pickle.dumps({"key": key, "blob": blob},
                           protocol=pickle.HIGHEST_PROTOCOL)
    framed = (AOT_MAGIC
              + _HEADER.pack(len(payload), zlib.crc32(payload))
              + payload)
    tmp = path + ".tmp"
    with open(tmp, "wb") as fd:
        fd.write(framed)
        fd.flush()
        os.fsync(fd.fileno())
    os.replace(tmp, path)
    checkpoint_mod._fsync_dir(path)
    kind = chaos.take_aot_corrupt()
    if kind == "bitflip":
        with open(path, "r+b") as fd:
            fd.seek(len(framed) // 2)
            byte = fd.read(1)
            fd.seek(len(framed) // 2)
            fd.write(bytes([byte[0] ^ 0x40]))
    elif kind == "skew":
        skewed = dict(key, jax="0.0.0-chaos-skew")
        payload = pickle.dumps({"key": skewed, "blob": blob},
                               protocol=pickle.HIGHEST_PROTOCOL)
        with open(path, "wb") as fd:
            fd.write(AOT_MAGIC
                     + _HEADER.pack(len(payload), zlib.crc32(payload))
                     + payload)


def load_artifact(path: str, want_key: dict):
    """Read one artifact back, fully validated BEFORE deserialization:
    magic, header, length, CRC, payload decode, then the stored
    fingerprint against ``want_key`` (an intact envelope built by a
    different jax/kernel/silicon is ``stale``, not loadable). Returns
    the ``jax.export.Exported``; raises :class:`ArtifactError`."""
    from jax import export as jax_export

    try:
        with open(path, "rb") as fd:
            framed = fd.read()
    except OSError as e:
        raise ArtifactError(
            "corrupt", f"unreadable AOT artifact at {path} "
            f"({type(e).__name__}: {e})") from e
    head = len(AOT_MAGIC) + _HEADER.size
    if not framed.startswith(AOT_MAGIC):
        raise ArtifactError(
            "corrupt", f"AOT artifact at {path} has a bad magic header — "
            "not a MOMP-AOT/1 file (or corrupted at offset 0)")
    if len(framed) < head:
        raise ArtifactError(
            "corrupt", f"AOT artifact at {path} is truncated inside its "
            f"header ({len(framed)} of {head} header bytes)")
    length, want_crc = _HEADER.unpack(framed[len(AOT_MAGIC):head])
    payload = framed[head:]
    if len(payload) != length:
        raise ArtifactError(
            "corrupt", f"AOT artifact at {path} is truncated: payload is "
            f"{len(payload)} bytes, header promises {length}")
    if zlib.crc32(payload) != want_crc:
        raise ArtifactError(
            "corrupt", f"AOT artifact at {path} failed its CRC "
            f"(stored {want_crc:#010x}, recomputed "
            f"{zlib.crc32(payload):#010x}) — the file is corrupt")
    try:
        doc = pickle.loads(payload)
        stored_key, blob = doc["key"], doc["blob"]
    except Exception as e:  # noqa: BLE001 — any decode failure
        raise ArtifactError(
            "corrupt", f"AOT artifact at {path} passed its CRC but failed "
            f"to decode ({type(e).__name__}: {e})"[:400]) from e
    if stored_key != want_key:
        drift = sorted(k for k in set(stored_key) | set(want_key)
                       if stored_key.get(k) != want_key.get(k))
        raise ArtifactError(
            "stale", f"AOT artifact at {path} is key-stale (fields "
            f"drifted: {drift}) — built by a different "
            "jax/kernel/silicon; rebuilding")
    try:
        return jax_export.deserialize(blob)
    except Exception as e:  # noqa: BLE001 — a blob only jax can judge
        raise ArtifactError(
            "corrupt", f"AOT artifact at {path} failed jax.export "
            f"deserialization ({type(e).__name__}: {e})"[:400]) from e


def _bucket_program(boards, steps):
    # The exact program the daemon's primary rung dispatches: the
    # batched native-path dispatcher with the step count flowing through
    # as a runtime scalar.
    from mpi_and_open_mp_tpu.ops import pallas_life

    return pallas_life.life_run_vmem_batch(boards, steps)


class AOTCache:
    """On-disk + in-memory store of the daemon's bucket executables.

    ``ensure`` is the one entry point the dispatch path uses: in-memory
    program, else load-from-disk (hit), else build+persist (miss); a
    bad artifact is quarantined and rebuilt. Every outcome lands in
    ``stats()`` (the daemon CLI/bench fields), the metrics registry
    (``serve.aot{status=...}``), and the trace stream — cold starts and
    cache rot are observable, never silent. Any cache-side failure
    degrades to ``(digest, None, "error")``: the daemon then simply
    serves through its normal trace-and-compile ladder."""

    def __init__(self, root: str | os.PathLike):
        self.root = os.path.abspath(os.fspath(root))
        os.makedirs(self.root, exist_ok=True)
        self._programs: dict[str, object] = {}
        self._verified: set[str] = set()
        self._stats = {"hits": 0, "misses": 0, "corrupt": 0, "stale": 0,
                       "parity_failed": 0, "built": 0, "errors": 0,
                       "deserialize_s": 0.0, "build_s": 0.0}

    # -- accounting --------------------------------------------------------

    def stats(self) -> dict:
        out = dict(self._stats)
        out["deserialize_s"] = round(out["deserialize_s"], 6)
        out["build_s"] = round(out["build_s"], 6)
        out["programs"] = len(self._programs)
        return out

    def _note(self, status: str, **fields) -> None:
        from mpi_and_open_mp_tpu.obs import metrics, trace

        metrics.inc("serve.aot", status=status)
        trace.event("serve.aot", status=status, **fields)

    # -- the dispatch-path entry point -------------------------------------

    def ensure(self, stack_shape, dtype) -> tuple[str, object, str]:
        """``(digest, exported_or_None, status)`` for one bucket program.

        ``status``: ``"memory"`` (already resident), ``"hit"``
        (deserialized from disk), ``"miss"`` (no artifact — freshly
        traced, exported, and persisted for the next process),
        ``"corrupt"``/``"stale"`` (bad artifact quarantined, then a
        fresh build — the ``aot:corrupt`` provenance path), ``"error"``
        (cache unavailable; ``exported`` is None and the caller serves
        without it)."""
        try:
            key = fingerprint(stack_shape, dtype)
            digest = digest_for(key)
        except Exception as e:  # noqa: BLE001 — keying must not kill serve
            self._stats["errors"] += 1
            self._note("error", error=f"{type(e).__name__}: {e}"[:200])
            return "", None, "error"
        if digest in self._programs:
            return digest, self._programs[digest], "memory"
        path = os.path.join(self.root, digest + ".aot")
        status = "miss"
        if os.path.exists(path):
            t0 = time.perf_counter()
            try:
                exp = load_artifact(path, key)
            except ArtifactError as e:
                status = e.kind  # "corrupt" | "stale"
                self._stats[e.kind] += 1
                quarantined = checkpoint_mod.quarantine(path, label=e.kind)
                self._note(e.kind, digest=digest,
                           quarantined=quarantined or "",
                           error=str(e)[:200])
            else:
                self._stats["hits"] += 1
                self._stats["deserialize_s"] += time.perf_counter() - t0
                self._note("hit", digest=digest)
                self._programs[digest] = exp
                return digest, exp, "hit"
        if status == "miss":
            self._stats["misses"] += 1
            self._note("miss", digest=digest)
        # Fresh trace: build the program this process needs anyway, and
        # persist it so the NEXT process resumes warm.
        t0 = time.perf_counter()
        try:
            exp = self._build(stack_shape, dtype)
            self._stats["build_s"] += time.perf_counter() - t0
            self._stats["built"] += 1
            save_artifact(path, key, exp.serialize())
        except Exception as e:  # noqa: BLE001 — never crash the daemon
            self._stats["errors"] += 1
            self._note("error", digest=digest,
                       error=f"{type(e).__name__}: {e}"[:200])
            return digest, None, "error"
        self._programs[digest] = exp
        return digest, exp, status

    def _build(self, stack_shape, dtype):
        import jax
        import jax.numpy as jnp
        from jax import export as jax_export

        args = (jax.ShapeDtypeStruct(tuple(stack_shape), np.dtype(dtype)),
                jax.ShapeDtypeStruct((), jnp.int32))
        return jax_export.export(jax.jit(_bucket_program))(*args)

    def warm(self, boards, max_batch: int) -> dict:
        """The preload phase: ensure every bucket program for the given
        ``(shape, dtype)`` pairs across all dispatchable bucket sizes up
        to ``max_batch`` (:func:`bucket_sizes` — powers of two plus the
        bitsliced plane multiples) — on a warm cache this is pure deserialization
        (milliseconds); on a cold one it is the plan/compile-once pass
        whose artifacts make every later restart warm. Returns the
        stats delta for this pass."""
        before = dict(self._stats)
        seen = set()
        for shape, dtype in boards:
            ny, nx = (int(x) for x in shape)
            for b in bucket_sizes(max_batch):
                sig = (b, ny, nx, str(np.dtype(dtype)))
                if sig in seen:
                    continue
                seen.add(sig)
                self.ensure((b, ny, nx), dtype)
        out = {k: (round(self._stats[k] - before[k], 6)
                   if isinstance(before[k], float)
                   else self._stats[k] - before[k])
               for k in before}
        out["programs"] = len(seen)
        return out

    # -- verified execution ------------------------------------------------

    def call_verified(self, digest: str, stack: np.ndarray, steps: int):
        """Run one resident program, oracle parity-gating its FIRST
        result per process: a deserialized executable earns trust by
        reproducing the NumPy oracle bit-exactly once, after which the
        per-dispatch validator (shape + value range) suffices. A parity
        failure quarantines the on-disk artifact, evicts the program,
        and raises :class:`ParityError` — the guards ladder then
        recovers through a fresh trace."""
        import jax.numpy as jnp

        from mpi_and_open_mp_tpu.ops.life_ops import life_step_numpy

        exp = self._programs[digest]
        out = np.asarray(exp.call(jnp.asarray(stack),
                                  jnp.int32(int(steps))))
        if digest not in self._verified:
            ref = np.array(stack, copy=True)
            for b in range(ref.shape[0]):
                board = ref[b]
                for _ in range(int(steps)):
                    board = life_step_numpy(board)
                ref[b] = board
            if not np.array_equal(out, ref):
                self._stats["parity_failed"] += 1
                self._programs.pop(digest, None)
                path = os.path.join(self.root, digest + ".aot")
                quarantined = (checkpoint_mod.quarantine(path)
                               if os.path.exists(path) else None)
                self._note("parity_failed", digest=digest,
                           quarantined=quarantined or "")
                raise ParityError(
                    f"AOT program {digest} diverged from the NumPy oracle "
                    "on first use — artifact quarantined")
            self._verified.add(digest)
        return out
