"""Serving fleet harnesses: N workers under one FleetRouter.

Two deployments of the same :class:`~mpi_and_open_mp_tpu.serve.router.
FleetRouter` contract:

* :class:`Fleet` — N in-process :class:`ServingDaemon` workers sharing
  one injectable clock. This is what ``bench.py --serve --fleet N`` and
  the unit tests drive: deterministic, no subprocess spawn tax, wedges
  simulated by halting a worker's pump (its heartbeat stops, the router
  declares it, the WAL replay + re-home ladder runs for real against
  the worker's real journal).
* The module CLI (``python -m mpi_and_open_mp_tpu.serve.fleet``) — the
  cross-process deployment CI's ``fleet-chaos-smoke`` kills for real: a
  parent partitions a seeded burst by consistent hash, writes one spool
  per worker, spawns one subprocess per worker (``--worker-main``),
  and when a worker dies (rc 137 from the ``kill_worker=<i>:<k>`` chaos
  token — indistinguishable from ``kill -9``) replays the victim's WAL,
  journals the ``re-homed`` sheds back to it, and spawns recovery
  workers for the re-homed entries on the surviving ring. One JSON line
  with the fleet books; the parity gate (``--verify``) covers every
  resolved ticket INCLUDING the re-homed ones.

The reference repo's answer to scale was a PBS multi-node launch
(``qsub -l nodes=N`` + ``mpirun``) whose answer to failure was "requeue
the whole job"; here the unit of failure is one worker, the unit of
recovery is one ticket, and the books must balance fleet-wide either
way (``docs/DESIGN.md`` §13).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

from mpi_and_open_mp_tpu.obs import metrics as obs_metrics
from mpi_and_open_mp_tpu.obs import telemetry as telemetry_mod
from mpi_and_open_mp_tpu.obs import trace as obs_trace
from mpi_and_open_mp_tpu.serve import policy as policy_mod
from mpi_and_open_mp_tpu.serve import wal as wal_mod
from mpi_and_open_mp_tpu.serve.daemon import ServingDaemon, _parse_shapes
from mpi_and_open_mp_tpu.serve.policy import ServePolicy, percentile
from mpi_and_open_mp_tpu.serve.queue import DONE, SHED, Ticket
from mpi_and_open_mp_tpu.serve.router import (
    DEFAULT_MISS_K, DEFAULT_VNODES, FleetRollup, FleetRouter)
from mpi_and_open_mp_tpu.utils import checkpoint as checkpoint_mod

SPOOL_SCHEMA = "momp-fleet-spool/1"


@dataclasses.dataclass
class WorkerHandle:
    """One worker as the router sees it: identity, daemon, journal
    path, and liveness. ``halted`` is the in-process wedge simulation
    (the fleet loop stops pumping it, so its heartbeat goes stale);
    ``wedged`` is the router's verdict and is never cleared.

    The membership flags: ``warming`` marks a worker deserializing its
    AOT cache after a spawn or REJOIN — alive but not yet pumping, so
    the fleet loop stamps its beat in the shared post-round beat (the
    same cover the slow-pump fix gives a compiling worker) until its
    first completed pump clears the flag. ``cordoned`` means the router
    took it off the ring mid-drain; ``drained`` is the graceful-exit
    terminal state (like ``wedged``, never cleared — a returning worker
    REJOINS under a fresh handle)."""

    index: int
    daemon: ServingDaemon
    wal_path: str | None = None
    last_beat: float = 0.0
    wedged: bool = False
    halted: bool = False
    warming: bool = False
    cordoned: bool = False
    drained: bool = False


class Fleet:
    """N in-process workers behind one router, one injectable clock.

    ``policies`` (one per worker) overrides the uniform ``policy`` —
    fleet workers may run heterogeneous budgets (the rollup projection
    and the per-worker doors are exercised either way). With a
    ``wal_dir`` every worker journals to ``<wal_dir>/worker<i>.wal``
    and a wedge re-homes from the journal replay; without one the
    re-home falls back to the live queue snapshot.
    """

    def __init__(self, n_workers: int, policy: ServePolicy | None = None,
                 *, policies: list[ServePolicy] | None = None,
                 wal_dir: str | None = None,
                 wal_fsync: str = "every-record",
                 heartbeat_interval_s: float = 0.02,
                 heartbeat_miss_k: int = DEFAULT_MISS_K,
                 steal: bool = True,
                 elasticity: policy_mod.ElasticityPolicy | None = None,
                 elastic_window_s: float = 1.0,
                 telemetry: bool | None = None,
                 telemetry_interval_s: float | None = None,
                 vnodes: int = DEFAULT_VNODES, seed: int = 0,
                 clock=time.monotonic, sleep=time.sleep):
        if n_workers < 1:
            raise ValueError(f"n_workers must be >= 1, got {n_workers}")
        if policies is not None and len(policies) != n_workers:
            raise ValueError(
                f"got {len(policies)} policies for {n_workers} workers")
        if policies is None:
            policies = [policy or ServePolicy()] * n_workers
        self._clock = clock
        self._sleep = sleep
        self._steal_enabled = steal
        self._wal_dir = wal_dir
        self._wal_fsync = wal_fsync
        self._spawn_policy = policies[-1]
        #: SLO-driven scaling: None = fixed fleet (the default — scaling
        #: is an OPERATOR policy, opted into per deployment). With a
        #: policy, every pump round feeds the hysteresis controller a
        #: rolling-window p99 + fleet depth; ``add`` spawns a warming
        #: worker, ``drain`` gracefully retires the shallowest one.
        self.controller = (policy_mod.ElasticController(elasticity)
                           if elasticity is not None else None)
        self._elastic_window_s = float(elastic_window_s)
        #: The telemetry plane: per-worker snapshot recorders shipped
        #: into the router's FleetRollup on the shared post-round beat
        #: (snapshots piggyback the heartbeat — a worker alive enough to
        #: beat is alive enough to report), plus the multi-window SLO
        #: burn-rate monitor whose window values every scale/drain
        #: decision records. ``MOMP_TELEMETRY=0`` (or telemetry=False)
        #: turns the whole plane off.
        self._telemetry_on = (telemetry_mod.telemetry_on()
                              if telemetry is None else bool(telemetry))
        self._telemetry_interval_s = (
            telemetry_mod.snapshot_interval_s()
            if telemetry_interval_s is None else float(telemetry_interval_s))
        epol = elasticity or policy_mod.ElasticityPolicy()
        self.burn = telemetry_mod.BurnRateMonitor(
            slo_p99_s=epol.slo_p99_s, goodput_frac=epol.slo_goodput_frac,
            short_window_s=self._elastic_window_s / 4,
            long_window_s=self._elastic_window_s,
        ) if self._telemetry_on else None
        #: Recorded elasticity decisions, each carrying the burn-rate
        #: window values that triggered it — the queryable record the
        #: ISSUE's "every decision explainable from recorded data" asks
        #: for (also emitted as ``serve.fleet.scale`` trace events).
        self.decisions: list[dict] = []
        self._wtel: dict[int, telemetry_mod.WorkerTelemetry] = {}
        self._tel_seen: dict[int, set] = {}
        self._tel_counts: dict[int, dict] = {}
        self._door_seen = 0
        self.handles: list[WorkerHandle] = []
        for i in range(n_workers):
            wal_path = (os.path.join(wal_dir, f"worker{i}.wal")
                        if wal_dir else None)
            d = ServingDaemon(policies[i], wal_path=wal_path,
                              wal_fsync=wal_fsync, worker_index=i,
                              clock=clock, sleep=sleep)
            self.handles.append(WorkerHandle(
                index=i, daemon=d, wal_path=wal_path, last_beat=clock()))
        self.router = FleetRouter(
            self.handles, vnodes=vnodes, seed=seed,
            heartbeat_interval_s=heartbeat_interval_s,
            heartbeat_miss_k=heartbeat_miss_k)

    # -- traffic -----------------------------------------------------------

    def submit(self, board, steps: int, session: str | None = None) -> Ticket:
        return self.router.submit(board, steps, self._clock(),
                                  session=session)

    def create_session(self, session: str, board):
        """Admit a resident session into its affinity worker's device
        pool (the ring is the session→pool map)."""
        return self.router.create_session(session, board, self._clock())

    def step_session(self, session: str, steps: int) -> Ticket:
        return self.router.step_session(session, steps, self._clock())

    def snapshot_session(self, session: str):
        return self.router.snapshot_session(session)

    def evict_session(self, session: str):
        return self.router.evict_session(session)

    def wedge(self, index: int) -> None:
        """Simulate a wedged worker: stop pumping it. Its heartbeat
        goes stale and the ROUTER must notice (``check_health``) —
        nothing here shortcuts the detection ladder."""
        for h in self.handles:
            if h.index == index:
                h.halted = True
                return
        raise ValueError(f"no worker with index {index}")

    # -- elastic membership --------------------------------------------------

    def _handle_at(self, index: int) -> WorkerHandle:
        for h in self.handles:
            if h.index == index:
                return h
        raise ValueError(f"no worker with index {index}")

    def rejoin_worker(self, index: int) -> int:
        """Bring a wedged (or drained) worker back: resume a FRESH
        daemon from the victim's own journal — the WAL handshake; a
        completed wedge re-home left it holding only the work the fleet
        never reassigned, so the rejoiner adopts exactly its claimed
        sessions and nothing else — then re-enter the ring under the
        old index (bounded movement: the old points come back, nothing
        else shifts) and claim back the whole slab groups that hash to
        it. The handle rejoins WARMING: the shared post-round beat
        covers it while the AOT cache deserializes, so the wedge
        horizon cannot re-declare it mid-warmup. Returns the number of
        sessions claimed."""
        old = self._handle_at(index)
        if not (old.wedged or old.drained):
            raise ValueError(
                f"worker {index} is live; rejoin re-admits a wedged or "
                "drained worker")
        d, _source, detail = ServingDaemon.resume_any(
            wal_path=old.wal_path, policy=old.daemon.policy,
            wal_fsync=self._wal_fsync, worker_index=index,
            clock=self._clock, sleep=self._sleep)
        fresh = WorkerHandle(index=index, daemon=d,
                             wal_path=old.wal_path,
                             last_beat=self._clock(), warming=True)
        claimed = self.router.rejoin_worker(fresh, self._clock())
        # The old handle leaves the pump loop but stays on the router's
        # retired list: its queue's history keeps counting in the books.
        self.handles[self.handles.index(old)] = fresh
        return claimed

    def drain_worker(self, index: int) -> dict:
        """Gracefully retire a live worker: cordon, migrate whole
        buckets and whole slab groups to the survivors, compact + sync
        its journal as the handoff receipt. Zero acked loss by
        construction — every pending entry adopts at its destination
        before the source sheds it."""
        return self.router.drain_worker(index, self._clock())

    def spawn_worker(self) -> WorkerHandle:
        """Add a brand-new worker under the next free index (the
        elasticity ``add`` verb). It joins WARMING — the post-round beat
        covers its AOT deserialization — and the ring/rollup widen via
        :meth:`FleetRouter.add_worker`."""
        index = max(h.index for h in self.handles) + 1
        wal_path = (os.path.join(self._wal_dir, f"worker{index}.wal")
                    if self._wal_dir else None)
        d = ServingDaemon(self._spawn_policy, wal_path=wal_path,
                          wal_fsync=self._wal_fsync, worker_index=index,
                          clock=self._clock, sleep=self._sleep)
        h = WorkerHandle(index=index, daemon=d, wal_path=wal_path,
                         last_beat=self._clock(), warming=True)
        self.router.add_worker(h)
        self.handles.append(h)
        return h

    def _autoscale(self, now: float) -> None:
        """One elasticity tick: rolling-window p99 + fleet depth into
        the hysteresis controller; act on its verdict. The controller
        owns the flap protection (breach/surplus streaks + cooldown);
        the fleet owns the verbs."""
        window = self._elastic_window_s
        lat = [t.latency_s for t in self.resolved_tickets()
               if t.resolved_at is not None
               and now - t.resolved_at <= window]
        p99 = percentile(lat, 99) if lat else 0.0
        live = self.router.live_workers()
        depth = self.pending()
        verdict = self.controller.observe(
            p99_s=p99, depth=depth, workers=len(live))
        if verdict is not None:
            # Every scale/drain verdict lands as recorded telemetry
            # WITH the burn-rate window values that triggered it — the
            # decision must be explainable from the recorded data alone.
            decision = {
                "action": verdict, "p99_s": round(p99, 6), "depth": depth,
                "workers": len(live), "mono": round(now, 6),
                **(self.burn.windows(now) if self.burn is not None else {}),
            }
            self.decisions.append(decision)
            obs_metrics.inc("serve.fleet.scale_decisions", action=verdict)
            obs_trace.event("serve.fleet.scale", **decision)
        if verdict == policy_mod.SCALE_ADD:
            self.spawn_worker()
        elif verdict == policy_mod.SCALE_DRAIN and len(live) > 1:
            # The shallowest live worker has the least to migrate; never
            # the last one.
            victim = min(
                (w for w in live if not getattr(w, "warming", False)),
                key=lambda w: w.daemon.queue.depth(), default=None)
            if victim is not None and len(live) > 1:
                self.router.drain_worker(victim.index, now)

    # -- the fleet loop ----------------------------------------------------

    def pump(self, *, drain: bool = False) -> int:
        """One fleet round: deliver any bucket parked mid-steal, every
        live worker pumps (its beat), then health check, a steal round,
        and the elasticity tick. Returns batches dispatched."""
        self.router.deliver_in_transit(self._clock())
        n = 0
        pumped = []
        for h in self.handles:
            if h.wedged or h.halted or h.drained:
                continue
            n += h.daemon.pump(self._clock(), drain=drain)
            pumped.append(h)
        # One shared post-round beat: a worker that just pumped is alive
        # by definition, however long the round took (first dispatches
        # compile for whole seconds — per-worker stamps taken mid-round
        # would look stale against the round-end clock and false-wedge
        # healthy workers). The beat also covers WARMING workers — a
        # rejoiner deserializing its AOT cache is alive but has not
        # pumped yet; without the stamp the wedge horizon would re-
        # declare it mid-warmup (the rejoin twin of the slow-pump
        # false wedge). Only never-pumped (halted) workers go stale.
        now = self._clock()
        for h in pumped:
            h.last_beat = now
            h.warming = False  # first completed pump ends the warmup
        for h in self.handles:
            if h.warming and not (h.wedged or h.drained):
                h.last_beat = now
        self.router.check_health(now)
        if self._steal_enabled:
            self.router.steal(self._clock(), defer=True)
        if self._telemetry_on:
            # Snapshot shipping rides the same post-round beat: the
            # telemetry tick runs BEFORE the elasticity tick, so a
            # burn-rate alert is on the record before any decision it
            # triggers (the merged timeline shows cause, then action).
            self._telemetry_tick(now)
        if self.controller is not None:
            self._autoscale(now)
        return n

    # -- telemetry ---------------------------------------------------------

    def _worker_telemetry(self, h: WorkerHandle):
        """The recorder for one handle LIFETIME (a rejoin's fresh handle
        gets a fresh series under the same worker index)."""
        wt = self._wtel.get(id(h))
        if wt is None:
            wt = telemetry_mod.WorkerTelemetry(
                h.index, interval_s=self._telemetry_interval_s)
            self._wtel[id(h)] = wt
            self._tel_seen[id(h)] = set()
            self._tel_counts[id(h)] = {"resolved": 0, "shed": 0}
        return wt

    def _telemetry_tick(self, now: float, *, force: bool = False) -> None:
        """Ship every due worker's snapshot into the router's rollup and
        feed the burn monitor the interval's good/bad counts. Interval-
        gated per worker; ``force`` flushes everyone (the end-of-run
        sample that makes surviving workers lose zero telemetry)."""
        good = bad = 0
        sampled = False
        for h in self.handles:
            if h.wedged or h.drained:
                continue  # frozen books; the last live sample stands
            wt = self._worker_telemetry(h)
            if not (force or wt.due(now)):
                continue
            seen = self._tel_seen[id(h)]
            counts = self._tel_counts[id(h)]
            for t in h.daemon.queue.tickets():
                if t.id in seen:
                    continue
                if t.state == DONE:
                    seen.add(t.id)
                    counts["resolved"] += 1
                    wt.observe_latency(t.latency_s)
                    if self.burn is not None and \
                            self.burn.is_bad(t.latency_s):
                        bad += 1
                    else:
                        good += 1
                elif (t.state == SHED
                      and t.reason != policy_mod.SHED_REHOMED):
                    # A real shed spends error budget; a re-homed ticket
                    # is a move, not an outcome — it resolves (or sheds)
                    # at its final owner and is judged there.
                    seen.add(t.id)
                    counts["shed"] += 1
                    bad += 1
            snap = wt.sample(now, {
                **counts, "depth": h.daemon.queue.depth(),
            }, force=force)
            if snap is not None:
                self.router.telemetry.ingest(snap)
                sampled = True
        if self.burn is None or not sampled:
            return
        door = sum(self.router.door_shed.values())
        bad += door - self._door_seen
        self._door_seen = door
        win = self.burn.observe(now, good, bad)
        if win.pop("alert_edge", False):
            obs_metrics.inc("serve.fleet.burn_alerts")
            obs_trace.event("serve.fleet.burn", mono=round(now, 6), **win)

    def pending(self) -> int:
        return (sum(h.daemon.queue.depth() for h in self.handles)
                + self.router.in_transit_depth())

    def serve_until_drained(self, *, drain: bool = False,
                            timeout_s: float = 120.0) -> None:
        """Pump until every admitted ticket fleet-wide is terminal. A
        halted worker's pending set drains via the wedge ladder: its
        beat goes stale while the loop idles, ``check_health`` declares
        it, and the re-homed tickets finish on the survivors."""
        start = self._clock()
        while self.pending():
            n = self.pump(drain=drain)
            if n == 0:
                self._sleep(max(1e-4, self.router.heartbeat_interval_s))
            if self._clock() - start > timeout_s:
                raise RuntimeError(
                    f"fleet failed to drain within {timeout_s}s "
                    f"({self.pending()} tickets pending)")
        if self._telemetry_on:
            # Final forced flush: every surviving worker's last interval
            # ships, so the rollup loses zero telemetry from survivors
            # (dead workers lose at most their final interval, counted).
            self._telemetry_tick(self._clock(), force=True)
        for h in self.handles:
            if h.daemon._wal is not None and not h.wedged:
                h.daemon._wal.sync()

    # -- accounting --------------------------------------------------------

    def resolved_tickets(self) -> list[Ticket]:
        """Every resolved ticket fleet-wide, INCLUDING the pre-failure
        lifetimes of rejoined workers (retired handles) — the parity
        gate and latency percentiles must cover work resolved before a
        membership change, not just the current roster's."""
        handles = list(self.handles) + list(self.router._retired)
        return [t for h in handles
                for t in h.daemon.queue.tickets() if t.state == DONE]

    def summary(self) -> dict:
        """Fleet books + aggregate latency over every resolved ticket
        (re-homed tickets carry their full cross-worker latency via the
        queued-seconds carry)."""
        books = self.router.books()
        lat = [t.latency_s for t in self.resolved_tickets()]
        books.update({
            "workers": len(self.handles),
            "wedged": list(self.router.wedged_workers),
            "drained": list(self.router.drained_workers),
            "p50_latency_s": round(percentile(lat, 50), 6),
            "p99_latency_s": round(percentile(lat, 99), 6),
        })
        return books


# -- cross-process CLI -----------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mpi_and_open_mp_tpu.serve.fleet",
        description="Sharded serving fleet driver: partition a seeded "
        "burst across N worker subprocesses by consistent-hash session "
        "affinity, survive worker deaths by WAL replay + re-home, print "
        "ONE JSON line with the fleet books. The MOMP_CHAOS "
        "kill_worker=<i>:<k> token hard-kills worker <i> mid-dispatch "
        "(rc 137) — the books must still balance with zero acked loss.")
    p.add_argument("--workers", type=int, default=3, metavar="N")
    p.add_argument("--requests", type=int, default=48, metavar="R")
    p.add_argument("--sessions", type=int, default=12, metavar="S",
                   help="distinct session keys cycled over the burst "
                   "(default %(default)s)")
    p.add_argument("--shapes", default="48x48,64x64", metavar="S")
    p.add_argument("--steps", default="4,8", metavar="K")
    p.add_argument("--max-batch", type=int, default=4)
    p.add_argument("--max-depth", type=int, default=4096)
    p.add_argument("--max-wait", type=float, default=0.02, metavar="S")
    p.add_argument("--timeout", type=float, default=60.0, metavar="S")
    p.add_argument("--max-padding-frac", type=float, default=0.375)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--vnodes", type=int, default=DEFAULT_VNODES)
    p.add_argument("--dir", default=None, metavar="PATH",
                   help="state directory for spools/journals/worker "
                   "logs (default: a fresh temp dir)")
    p.add_argument("--verify", action="store_true",
                   help="each worker gates every resolved board "
                   "bit-exact against the NumPy oracle — including the "
                   "re-homed tickets on recovery workers")
    p.add_argument("--slo-p99", type=float, default=0.25, metavar="S",
                   help="latency SLO threshold the telemetry plane "
                   "classifies resolved tickets against (default "
                   "%(default)s s)")
    # Internal: run as one fleet worker over a spool file.
    p.add_argument("--worker-main", type=int, default=None, metavar="I",
                   help=argparse.SUPPRESS)
    p.add_argument("--spool", default=None, help=argparse.SUPPRESS)
    p.add_argument("--wal", default=None, help=argparse.SUPPRESS)
    p.add_argument("--telemetry-sidecar", default=None,
                   help=argparse.SUPPRESS)
    return p


def _policy(args) -> ServePolicy:
    return ServePolicy(
        max_batch=args.max_batch, max_depth=args.max_depth,
        max_padding_frac=args.max_padding_frac,
        max_wait_s=args.max_wait, request_timeout_s=args.timeout,
        seed=args.seed)


def _worker_main(args) -> int:
    """One fleet worker: drain a spool under the full daemon contract
    (WAL, chaos sites, supervision ladder), print one JSON line."""
    idx = args.worker_main
    spool = checkpoint_mod.restore_state(args.spool)
    if spool.get("schema") != SPOOL_SCHEMA:
        print(json.dumps({"worker": idx, "error": "bad spool schema"}))
        return 1
    daemon = ServingDaemon(_policy(args), wal_path=args.wal,
                           worker_index=idx)
    rehomed = [e for e in spool["entries"] if e.get("rehomed")]
    fresh = [e for e in spool["entries"] if not e.get("rehomed")]
    daemon.adopt(rehomed)
    for e in fresh:
        daemon.submit(e["board"], e["steps"], session=e.get("session"))

    shipper = None
    if args.telemetry_sidecar and telemetry_mod.telemetry_on():
        # The sidecar stream: a daemon thread frames periodic snapshots
        # into the per-worker file the parent merges post-run. A kill -9
        # stops the writer mid-frame at worst — the CRC framing bounds
        # the loss to this worker's final interval, and the parent
        # COUNTS it (`telemetry.loss`).
        seen: set = set()
        counts = {"resolved": 0, "shed": 0, "good": 0, "bad": 0}

        def _sample():
            new_lat = []
            for t in daemon.queue.tickets():
                if t.id in seen:
                    continue
                if t.state == DONE:
                    seen.add(t.id)
                    counts["resolved"] += 1
                    new_lat.append(t.latency_s)
                    if t.latency_s > args.slo_p99:
                        counts["bad"] += 1
                    else:
                        counts["good"] += 1
                elif t.state == SHED:
                    seen.add(t.id)
                    counts["shed"] += 1
                    if t.reason != policy_mod.SHED_REHOMED:
                        counts["bad"] += 1
            return (dict(counts, depth=daemon.queue.depth()), new_lat)

        shipper = telemetry_mod.SnapshotShipper(
            args.telemetry_sidecar, idx, _sample).start()

    t0 = time.perf_counter()
    try:
        daemon.serve(watch_signals=True)
    except Exception as e:  # noqa: BLE001 — the line IS the contract
        print(json.dumps({"worker": idx,
                          "error": f"{type(e).__name__}: {e}"[:300]}))
        return 1
    finally:
        if shipper is not None:
            shipper.stop()
    rec = {"worker": idx, "wall_sec": round(time.perf_counter() - t0, 4),
           **{k: v for k, v in daemon.summary().items() if k != "engines"}}
    if args.verify:
        from mpi_and_open_mp_tpu.serve.daemon import _verify

        rec["verified"] = _verify(daemon)
    if daemon._wal is not None:
        daemon._wal.close()
    print(json.dumps(rec))
    return 0 if (not args.verify or rec.get("verified")) else 1


def _spawn_worker(args, idx: int, spool_path: str, wal_path: str,
                  out_path: str, *, strip_chaos: bool = False):
    cmd = [sys.executable, "-m", "mpi_and_open_mp_tpu.serve.fleet",
           "--worker-main", str(idx), "--spool", spool_path,
           "--wal", wal_path,
           "--max-batch", str(args.max_batch),
           "--max-depth", str(args.max_depth),
           "--max-wait", str(args.max_wait),
           "--timeout", str(args.timeout),
           "--max-padding-frac", str(args.max_padding_frac),
           "--seed", str(args.seed),
           "--slo-p99", str(args.slo_p99)]
    if args.verify:
        cmd.append("--verify")
    env = dict(os.environ)
    stem = out_path[:-4] if out_path.endswith(".out") else out_path
    if telemetry_mod.telemetry_on():
        cmd += ["--telemetry-sidecar", stem + ".telemetry.bin"]
    if obs_trace.enabled():
        # Per-worker trace sink: every subprocess appends to its OWN
        # JSONL next to its stdout, so the merged Perfetto timeline
        # (analysis/fleet_report.py) gets one track per worker without
        # interleaved writes to the parent's file.
        env["MOMP_TRACE"] = stem + ".trace.jsonl"
    if strip_chaos:
        # Recovery workers run clean by the same convention as the
        # in-process ladder's chaos.suppressed(): the fault that killed
        # the victim must not re-kill the redo.
        env.pop("MOMP_CHAOS", None)
    out = open(out_path, "wb")
    err = open(out_path + ".err", "wb")
    return subprocess.Popen(cmd, stdout=out, stderr=err, env=env)


def _read_worker_line(out_path: str) -> dict | None:
    try:
        with open(out_path, "rb") as fd:
            lines = [ln for ln in fd.read().decode(
                "utf-8", "replace").splitlines() if ln.strip()]
    except OSError:
        return None
    for ln in reversed(lines):
        try:
            return json.loads(ln)
        except json.JSONDecodeError:
            continue
    return None


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.worker_main is not None:
        if not (args.spool and args.wal):
            build_parser().error("--worker-main requires --spool and --wal")
        return _worker_main(args)

    from mpi_and_open_mp_tpu.serve.router import (
        ConsistentHashRing, affinity_key)

    state_dir = args.dir or tempfile.mkdtemp(prefix="momp-fleet-")
    os.makedirs(state_dir, exist_ok=True)
    n = args.workers
    policy = _policy(args)
    roll = policy_mod.rollup([policy] * n)
    ring = ConsistentHashRing(range(n), vnodes=args.vnodes, seed=args.seed)

    # Partition the seeded burst by session affinity, with the driver
    # door applying the rolled-up + per-worker DEPTH budgets (padding
    # projection stays at each worker's own door — the driver holds no
    # queue to estimate against).
    shapes = _parse_shapes(args.shapes)
    step_list = [int(s) for s in args.steps.split(",")]
    rng = np.random.default_rng(args.seed)
    spools: dict[int, list[dict]] = {i: [] for i in range(n)}
    door_shed = 0
    for i in range(args.requests):
        ny, nx = shapes[i % len(shapes)]
        board = (rng.random((ny, nx)) < 0.3).astype(np.uint8)
        session = f"s{i % max(1, args.sessions):04d}"
        w = ring.lookup(affinity_key(session))
        total = sum(len(v) for v in spools.values())
        if total >= roll.max_depth or len(spools[w]) >= policy.max_depth:
            door_shed += 1
            continue
        spools[w].append({"board": board, "steps":
                          step_list[i % len(step_list)],
                          "session": session})

    t_start = time.perf_counter()
    procs = {}
    wal_paths = {}
    for i in range(n):
        spool_path = os.path.join(state_dir, f"worker{i}.spool")
        wal_paths[i] = os.path.join(state_dir, f"worker{i}.wal")
        checkpoint_mod.save_state(spool_path, {
            "schema": SPOOL_SCHEMA, "worker": i, "entries": spools[i]})
        procs[i] = _spawn_worker(
            args, i, spool_path, wal_paths[i],
            os.path.join(state_dir, f"worker{i}.out"))
    rcs = {i: p.wait() for i, p in procs.items()}
    lines = {i: _read_worker_line(os.path.join(state_dir, f"worker{i}.out"))
             for i in range(n)}

    # -- telemetry rollup: merge every worker's sidecar stream ---------
    tel_on = telemetry_mod.telemetry_on()
    rollup = FleetRollup() if tel_on else None
    burn = (telemetry_mod.BurnRateMonitor(slo_p99_s=args.slo_p99)
            if tel_on else None)
    scale_decisions: list[dict] = []

    def _ingest_sidecar(stem: str, worker_key=None) -> list[dict]:
        """Fold one sidecar file into the rollup; returns its snapshots
        (for the burn feed). Truncated tail frames charge loss."""
        rep = telemetry_mod.read_frames(stem + ".telemetry.bin")
        rollup.truncated += rep["truncated"]
        for s in rep["snapshots"]:
            rollup.ingest(s, worker=worker_key)
        return rep["snapshots"]

    def _feed_burn(streams: list[list[dict]]) -> None:
        """Replay the streams' good/bad counter deltas into the parent
        burn monitor on the shared WALL timeline (each worker stamps
        wall alongside mono — the clock-alignment exchange). Deltas
        from ALL streams merge-sort by wall first: the monitor's window
        pruning wants a monotone feed."""
        feed = []
        for snaps in streams:
            pg = pb = 0
            for s in snaps:
                c = s.get("counters") or {}
                g, b = int(c.get("good", 0)), int(c.get("bad", 0))
                feed.append((float(s["wall"]), g - pg, b - pb))
                pg, pb = g, b
        for wall_t, g, b in sorted(feed):
            win = burn.observe(wall_t, g, b)
            if win.pop("alert_edge", False):
                obs_metrics.inc("serve.fleet.burn_alerts")
                obs_trace.event("serve.fleet.burn",
                                wall=round(wall_t, 6), **win)

    if tel_on:
        _feed_burn([_ingest_sidecar(os.path.join(state_dir, f"worker{i}"))
                    for i in range(n)])

    # -- failure domain: replay each dead worker's WAL, re-home --------
    victims = [i for i, rc in rcs.items() if rc != 0]
    t_kill = time.perf_counter()
    rehomed = 0
    recovery_lines: list[dict] = []
    recovery_rcs: list[int] = []
    victim_resolved = victim_shed = 0
    for v in victims:
        rep = wal_mod.replay(wal_paths[v])
        victim_resolved += len(rep.resolved_ids)
        victim_shed += len(rep.shed_ids)
        if not rep.pending:
            continue
        # Journal the re-homed sheds back to the victim so a SECOND
        # replay (another recovery pass, forensics) finds nothing
        # pending — the same idempotence the in-process router keeps.
        w = wal_mod.TicketWAL(wal_paths[v])
        w.shed([e["id"] for e in rep.pending], policy_mod.SHED_REHOMED)
        w.close()
        ring.remove_worker(v)
        by_target: dict[int, list[dict]] = {}
        for e in rep.pending:
            key = affinity_key(e.get("session"), e.get("id"))
            by_target.setdefault(ring.lookup(key), []).append(e)
        rehomed += len(rep.pending)
        if tel_on:
            # The kill lands on the record BEFORE the autoscale verb:
            # the victim's lost pending set spends error budget NOW, the
            # burn event carries the window values, and only then does
            # the scale decision (spawn recovery capacity) follow — the
            # merged timeline shows cause, then action.
            now_wall = time.time()
            win = burn.observe(now_wall, 0, len(rep.pending))
            if win.pop("alert_edge", False):
                obs_metrics.inc("serve.fleet.burn_alerts")
            obs_trace.event("serve.fleet.burn", wall=round(now_wall, 6),
                            worker=v, pending=len(rep.pending), **win)
            decision = {
                "action": "add", "reason": "worker-death", "worker": v,
                "pending": len(rep.pending),
                "wall": round(time.time(), 6),
                **burn.windows(now_wall),
            }
            scale_decisions.append(decision)
            obs_metrics.inc("serve.fleet.scale_decisions", action="add")
            obs_trace.event("serve.fleet.scale", **decision)
        for tgt, group in by_target.items():
            spool_path = os.path.join(state_dir,
                                      f"worker{tgt}.rehome{v}.spool")
            checkpoint_mod.save_state(spool_path, {
                "schema": SPOOL_SCHEMA, "worker": tgt,
                "entries": [{**e, "rehomed": True} for e in group]})
            out = os.path.join(state_dir, f"worker{tgt}.rehome{v}.out")
            proc = _spawn_worker(
                args, tgt, spool_path,
                os.path.join(state_dir, f"worker{tgt}.rehome{v}.wal"),
                out, strip_chaos=True)
            recovery_rcs.append(proc.wait())
            recovery_lines.append(_read_worker_line(out) or {})
            if tel_on:
                # The recovery worker re-uses index `tgt` but is a new
                # lifetime: its stream rolls up under its own key.
                _feed_burn([_ingest_sidecar(
                    os.path.join(state_dir, f"worker{tgt}.rehome{v}"),
                    worker_key=f"{tgt}.rehome{v}")])
    recovery_s = time.perf_counter() - t_kill if victims else 0.0
    wall = time.perf_counter() - t_start

    # -- fleet books -------------------------------------------------------
    survivor_lines = [lines[i] or {} for i in range(n) if i not in victims]
    resolved = (sum(ln.get("resolved", 0) for ln in survivor_lines)
                + victim_resolved
                + sum(ln.get("resolved", 0) for ln in recovery_lines))
    shed = (sum(ln.get("shed", 0) for ln in survivor_lines)
            + victim_shed
            + sum(ln.get("shed", 0) for ln in recovery_lines))
    rehomed_resolved = sum(ln.get("resolved", 0) for ln in recovery_lines)
    acked = args.requests - door_shed
    acked_loss = acked - resolved - shed
    verified = None
    if args.verify:
        verified = all(ln.get("verified", False)
                       for ln in survivor_lines + recovery_lines)
    rec = {
        "fleet": n, "requests": args.requests, "sessions": args.sessions,
        "door_shed": door_shed,
        "worker_rcs": [rcs[i] for i in range(n)],
        "victims": victims,
        "recovery_rcs": recovery_rcs,
        "rehomed": rehomed,
        "rehomed_resolved": rehomed_resolved,
        "resolved": resolved, "shed": shed,
        "acked_loss": acked_loss,
        "books_balance": acked_loss == 0,
        "fleet_requests_per_sec": (round(resolved / wall, 2)
                                   if wall > 0 and resolved else 0.0),
        "fleet_p99_latency_s": round(max(
            [ln.get("p99_latency_s", 0.0)
             for ln in survivor_lines + recovery_lines] or [0.0]), 6),
        "fleet_kill_recovery_s": round(recovery_s, 4),
        "wall_sec": round(wall, 4),
        "state_dir": state_dir,
    }
    if verified is not None:
        rec["verified"] = verified
        rec["rehomed_parity"] = all(
            ln.get("verified", False) for ln in recovery_lines)
    if tel_on:
        rec["telemetry"] = {
            **rollup.summary(),
            **burn.summary(),
            "clock_offsets": rollup.clock_offsets(),
            "decisions": scale_decisions,
        }
    print(json.dumps(rec))
    ok = (rec["books_balance"]
          and all(rc == 0 for rc in recovery_rcs)
          and all(rcs[i] in (0, 137) for i in range(n))
          and (verified is None or verified))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
