"""Open-loop load generation: drive the fleet to saturation, honestly.

The reference repo's capacity story was a PBS sweep — ``qsub -l
nodes=N`` once per node count, eyeball the wall-clock table. Two things
are wrong with porting that shape to a serving fleet. First, it is
**closed-loop**: each client submits its next request only after the
previous one returns, so the generator slows down exactly when the
system does, and the measured latency at "full load" is a flattering
fiction (coordinated omission — the requests that WOULD have arrived
during a stall are simply never sent). Second, it measures throughput
alone; a serving fleet's contract is a latency SLO at an offered rate,
and throughput without the tail is not a capacity number.

This module is the open-loop replacement. Arrivals are a **schedule**,
not a reaction: :func:`arrivals_poisson` draws exponential
inter-arrival gaps for a target rate (:func:`arrivals_trace` replays a
recorded one), and :func:`run_open_loop` submits each request at its
scheduled instant whether or not the fleet has finished the previous
ones. When the fleet falls behind, queues deepen, the door sheds, and
the tail grows — which is the point: those are the numbers the SLO
judges. One run yields a :class:`LoadgenReport` (goodput + nearest-rank
p50/p99/p999 + shed breakdown + the fleet books); :func:`sweep` runs a
monotone offered-load ladder on fresh fleets and :func:`saturation_knee`
reads off the last rung that still meets the :class:`SLO` — the
capacity number ``bench.py --loadgen`` publishes.

Traffic is a :class:`ScenarioMix`, because a fleet that only ever sees
one-shot same-shape tickets is not under real load: the mix weights
one-shot batch tickets (mixed shapes — distinct compiled buckets),
resident-session steps (the pool fast path, placement-sticky), and
snapshot reads (synchronous device→host crossings that steal dispatch
time). Every request kind resolves to something the oracle can check —
the report keeps the resident create-boards so the caller can gate
snapshots bit-exact, and resolved tickets carry their boards for the
usual parity sweep.

Determinism: everything is seeded ``np.random.default_rng``; with the
fleet's injectable clock (tests use a fake clock whose ``sleep``
advances it) a run is exactly reproducible.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from mpi_and_open_mp_tpu.serve.policy import percentile
from mpi_and_open_mp_tpu.serve.queue import DONE, SHED

#: Scenario kinds a mix can weight. ``batch`` = one-shot board ticket
#: (no session affinity — spreads over the ring); ``resident`` = one
#: step ticket against a long-lived pooled session; ``snapshot`` = a
#: synchronous read of a pooled session's board.
SCENARIO_KINDS = ("batch", "resident", "snapshot")


@dataclasses.dataclass(frozen=True)
class ScenarioMix:
    """Weighted traffic composition for one loadgen run.

    ``shapes`` are the one-shot board shapes (each distinct shape is a
    distinct compiled bucket — mixing them loads the padding door and
    the AOT cache, not just the queue); ``steps`` the per-request step
    counts; ``sessions`` the number of long-lived resident sessions the
    run creates up front and then steps/snapshots at random. Weights
    are relative, not normalized."""

    batch: float = 1.0
    resident: float = 0.0
    snapshot: float = 0.0
    shapes: tuple = ((48, 48), (64, 64))
    steps: tuple = (2, 4)
    sessions: int = 0
    fill: float = 0.3

    def __post_init__(self):
        for kind in SCENARIO_KINDS:
            w = getattr(self, kind)
            if w < 0:
                raise ValueError(f"mix weight {kind} must be >= 0, got {w}")
        if self.batch + self.resident + self.snapshot <= 0:
            raise ValueError("mix weights must sum to > 0")
        if (self.resident > 0 or self.snapshot > 0) and self.sessions < 1:
            raise ValueError(
                "resident/snapshot traffic needs sessions >= 1")
        if not self.shapes or not self.steps:
            raise ValueError("mix needs at least one shape and one step")
        if not 0.0 < self.fill < 1.0:
            raise ValueError(f"fill must be in (0, 1), got {self.fill}")

    def weights(self) -> np.ndarray:
        w = np.array([self.batch, self.resident, self.snapshot], float)
        return w / w.sum()


@dataclasses.dataclass(frozen=True)
class SLO:
    """The declared service-level objective a run is judged against.

    ``p99_s``/``p999_s`` bound the measured latency percentiles over
    resolved tickets; ``goodput_frac`` demands the fleet actually
    complete that fraction of the offered rate (a fleet that sheds 60%
    of traffic can have a beautiful p99 — the survivors were cheap).
    ``p999_s=None`` skips the extreme-tail bound (short runs cannot
    estimate it honestly)."""

    p99_s: float = 0.25
    p999_s: float | None = None
    goodput_frac: float = 0.9

    def __post_init__(self):
        if self.p99_s <= 0:
            raise ValueError(f"p99_s must be > 0, got {self.p99_s}")
        if self.p999_s is not None and self.p999_s < self.p99_s:
            raise ValueError(
                f"p999_s ({self.p999_s}) must be >= p99_s ({self.p99_s})")
        if not 0.0 < self.goodput_frac <= 1.0:
            raise ValueError(
                f"goodput_frac must be in (0, 1], got {self.goodput_frac}")

    def verdict(self, *, goodput_rps: float, offered_rps: float,
                p99_s: float, p999_s: float) -> bool:
        ok = p99_s <= self.p99_s
        if self.p999_s is not None:
            ok = ok and p999_s <= self.p999_s
        return ok and goodput_rps >= self.goodput_frac * offered_rps


def arrivals_poisson(rate_rps: float, duration_s: float, *,
                     seed: int = 0) -> list[float]:
    """Poisson-process arrival offsets: exponential inter-arrival gaps
    at ``rate_rps``, truncated at ``duration_s``. The schedule exists
    BEFORE the run — an open-loop generator never consults the system
    under test about when to send."""
    if rate_rps <= 0:
        raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
    if duration_s <= 0:
        raise ValueError(f"duration_s must be > 0, got {duration_s}")
    rng = np.random.default_rng(seed)
    out: list[float] = []
    t = 0.0
    # Draw in chunks: the expected count is rate*duration; 2x + slack
    # covers the tail in one draw almost always, the loop covers the
    # rest exactly.
    while True:
        gaps = rng.exponential(1.0 / rate_rps,
                               size=max(16, int(2 * rate_rps * duration_s)))
        for g in gaps:
            t += float(g)
            if t >= duration_s:
                return out
            out.append(t)


def arrivals_trace(offsets) -> list[float]:
    """Validate a recorded arrival trace: offsets in seconds from run
    start, non-negative and non-decreasing. Replaying a trace turns a
    production incident into a regression test."""
    out = [float(x) for x in offsets]
    if any(x < 0 for x in out):
        raise ValueError("trace offsets must be >= 0")
    if any(b < a for a, b in zip(out, out[1:])):
        raise ValueError("trace offsets must be non-decreasing")
    return out


@dataclasses.dataclass
class LoadgenReport:
    """One open-loop run's results. ``resident_boards`` maps each
    resident session to its CREATE board so the caller can oracle-gate
    final snapshots; ``shed`` is reason→count over door + worker sheds
    combined."""

    offered_rps: float
    duration_s: float
    offered: int
    submitted: int
    resolved: int
    snapshots: int
    shed: dict
    goodput_rps: float
    p50_s: float
    p99_s: float
    p999_s: float
    slo_ok: bool
    wall_s: float
    books: dict
    resident_boards: dict = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        """The JSON-line projection (drops the board payloads)."""
        return {
            "offered_rps": round(self.offered_rps, 3),
            "offered": self.offered,
            "resolved": self.resolved,
            "goodput_rps": round(self.goodput_rps, 3),
            "p50_s": round(self.p50_s, 6),
            "p99_s": round(self.p99_s, 6),
            "p999_s": round(self.p999_s, 6),
            "shed": dict(self.shed),
            "slo_ok": bool(self.slo_ok),
        }


def _build_schedule(arrivals: list[float], mix: ScenarioMix,
                    seed: int) -> list[tuple]:
    """Bind each arrival instant to a concrete request: kind, payload.
    Seeded separately from the arrival draw so the same traffic rides
    every rung of a sweep (the mix is the controlled variable, the
    rate is the swept one)."""
    rng = np.random.default_rng(seed + 1)
    kinds = rng.choice(len(SCENARIO_KINDS), size=len(arrivals),
                       p=mix.weights())
    schedule = []
    for off, k in zip(arrivals, kinds):
        kind = SCENARIO_KINDS[int(k)]
        if kind == "batch":
            ny, nx = mix.shapes[int(rng.integers(len(mix.shapes)))]
            board = (rng.random((ny, nx)) < mix.fill).astype(np.uint8)
            steps = int(mix.steps[int(rng.integers(len(mix.steps)))])
            schedule.append((off, "batch", board, steps))
        elif kind == "resident":
            sid = f"r{int(rng.integers(mix.sessions)):04d}"
            steps = int(mix.steps[int(rng.integers(len(mix.steps)))])
            schedule.append((off, "resident", sid, steps))
        else:
            sid = f"r{int(rng.integers(mix.sessions)):04d}"
            schedule.append((off, "snapshot", sid, 0))
    return schedule


def run_open_loop(fleet, rate_rps: float, duration_s: float, *,
                  mix: ScenarioMix | None = None,
                  slo: SLO | None = None, seed: int = 0,
                  trace=None, events=None,
                  drain_timeout_s: float = 120.0) -> LoadgenReport:
    """Drive ``fleet`` open-loop for ``duration_s`` at ``rate_rps``
    (or over an explicit ``trace``), then drain, then judge.

    The loop per round: submit every request whose scheduled instant
    has passed (REGARDLESS of completions — that is the open loop),
    fire any due ``events`` (``[(frac_of_duration, fn(fleet)), ...]``
    — the membership drill hooks: wedge at 0.25, rejoin at 0.45, drain
    at 0.65), pump once, and sleep only when both the schedule and the
    queues are idle. Resident sessions are created up front and are
    NOT evicted — the report carries their create boards so the caller
    can snapshot + oracle-gate after the run.

    Latency honesty: a request's clock starts at its SCHEDULED
    submission (the fleet queue stamps it at ``submit``, which this
    loop calls at — not after — the scheduled instant), and sheds are
    never latency samples; they are failures, reported in ``shed`` and
    charged against goodput."""
    mix = mix or ScenarioMix()
    slo = slo or SLO()
    clock = fleet._clock
    sleep = fleet._sleep
    if trace is not None:
        arrivals = arrivals_trace(trace)
        duration_s = max([duration_s] + arrivals)
    else:
        arrivals = arrivals_poisson(rate_rps, duration_s, seed=seed)
    schedule = _build_schedule(arrivals, mix, seed)
    pending_events = sorted(events or [], key=lambda e: e[0])

    resident_boards: dict[str, np.ndarray] = {}
    rng = np.random.default_rng(seed + 2)
    for i in range(mix.sessions if (mix.resident or mix.snapshot) else 0):
        ny, nx = mix.shapes[int(rng.integers(len(mix.shapes)))]
        board = (rng.random((ny, nx)) < mix.fill).astype(np.uint8)
        sid = f"r{i:04d}"
        fleet.create_session(sid, board)
        resident_boards[sid] = board

    tickets = []
    snapshots = 0
    snapshot_lat: list[float] = []
    t0 = clock()
    i = 0
    ei = 0
    while i < len(schedule):
        now = clock()
        el = now - t0
        while i < len(schedule) and schedule[i][0] <= el:
            _, kind, payload, steps = schedule[i]
            if kind == "batch":
                tickets.append(fleet.submit(payload, steps))
            elif kind == "resident":
                tickets.append(fleet.step_session(payload, steps))
            else:
                s0 = clock()
                fleet.snapshot_session(payload)
                snapshot_lat.append(clock() - s0)
                snapshots += 1
            i += 1
        while ei < len(pending_events) and \
                pending_events[ei][0] * duration_s <= el:
            pending_events[ei][1](fleet)
            ei += 1
        n = fleet.pump()
        if n == 0 and i < len(schedule):
            gap = schedule[i][0] - (clock() - t0)
            if gap > 0:
                sleep(min(gap, fleet.router.heartbeat_interval_s))
    # Late events (frac >= the last arrival's instant) still fire —
    # a drill scheduled at 0.9 must not silently vanish on a sparse
    # schedule.
    while ei < len(pending_events):
        pending_events[ei][1](fleet)
        ei += 1
    fleet.serve_until_drained(drain=True, timeout_s=drain_timeout_s)
    wall = max(clock() - t0, 1e-9)

    resolved = [t for t in tickets if t.state == DONE]
    shed: dict[str, int] = {}
    for t in tickets:
        if t.state == SHED:
            shed[t.reason] = shed.get(t.reason, 0) + 1
    lat = sorted(t.latency_s for t in resolved)
    p50 = percentile(lat, 50)
    p99 = percentile(lat, 99)
    p999 = percentile(lat, 99.9)
    goodput = len(resolved) / wall
    offered_rps = len(schedule) / max(duration_s, 1e-9)
    return LoadgenReport(
        offered_rps=offered_rps, duration_s=duration_s,
        offered=len(schedule), submitted=len(tickets),
        resolved=len(resolved), snapshots=snapshots, shed=shed,
        goodput_rps=goodput, p50_s=p50, p99_s=p99, p999_s=p999,
        slo_ok=slo.verdict(goodput_rps=goodput, offered_rps=offered_rps,
                           p99_s=p99, p999_s=p999),
        wall_s=wall, books=fleet.router.books(),
        resident_boards=resident_boards)


def sweep(fleet_factory, rates, duration_s: float, *,
          mix: ScenarioMix | None = None, slo: SLO | None = None,
          seed: int = 0) -> list[LoadgenReport]:
    """The offered-load ladder: one FRESH fleet per rung (warm state
    from a lower rate would flatter a higher one), strictly increasing
    rates, same seeded mix on every rung. Returns one report per
    rung; feed them to :func:`saturation_knee`."""
    rates = [float(r) for r in rates]
    if not rates:
        raise ValueError("sweep needs at least one rate")
    if any(b <= a for a, b in zip(rates, rates[1:])):
        raise ValueError(f"rates must be strictly increasing, got {rates}")
    return [run_open_loop(fleet_factory(), r, duration_s, mix=mix,
                          slo=slo, seed=seed) for r in rates]


def saturation_knee(reports: list[LoadgenReport]) -> dict:
    """Read the knee off a sweep: the highest offered rate that still
    met the SLO (``knee_rps``) and the first that breached
    (``breach_rps``; ``None`` while the fleet keeps up everywhere).
    ``knee_rps`` is the capacity number: offered load beyond it buys
    shed + tail, not goodput."""
    if not reports:
        raise ValueError("saturation_knee needs at least one report")
    knee = None
    breach = None
    for r in reports:
        if r.slo_ok:
            knee = r.offered_rps
        elif breach is None:
            breach = r.offered_rps
    return {
        "knee_rps": round(knee, 3) if knee is not None else None,
        "breach_rps": round(breach, 3) if breach is not None else None,
        "points": [r.to_dict() for r in reports],
    }
