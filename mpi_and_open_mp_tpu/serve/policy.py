"""Serving policy: admission control, load shedding, deadline budgets.

Pure decision logic — no clocks, no IO, no jax — so every admission and
shed rule is assertable in a unit test without running the daemon. The
daemon (``serve.daemon``) owns the side effects; this module owns the
numbers they are judged against.

The two admission budgets guard the two resources a shape-bucketed
server can actually exhaust:

* **Depth** — pending tickets queue host memory and, at ~70 ms RTT per
  dispatch through the relay, wall time: a queue deeper than the worker
  can drain inside the per-request timeout is already lost, so it is
  cheaper (and honest) to reject at the door with an explicit reason
  than to time the request out later.
* **Padding waste** — every bucket chunk pads its live requests up to a
  power of two, or to a 32-board plane multiple when the shape is
  bitsliced-eligible (``serve.batcher.bucket_batch_size``), so an
  adversarial request mix can make the device spend most of its cycles
  advancing dead zero-boards. :func:`padding_waste` estimates that fraction over
  the whole pending set; admission rejects a request whose acceptance
  pushes the estimate past budget.

Shed reasons are closed vocabulary (the ``SHED_*`` constants): every
rejected or abandoned ticket carries exactly one, metrics count them per
reason (``serve.shed{reason=...}``), and the chaos soak asserts no ticket
ever ends without either a result or one of these strings.
"""

from __future__ import annotations

import dataclasses
from collections.abc import Iterable

from mpi_and_open_mp_tpu.serve.batcher import bucket_batch_size

#: Admission rejected: pending depth at ``max_depth``.
SHED_DEPTH = "queue-depth"
#: Admission rejected: estimated padding waste past ``max_padding_frac``.
SHED_PADDING = "padding-waste"
#: Abandoned: the ticket aged past ``request_timeout_s`` before a
#: dispatch could resolve it (pathological shapes must not starve peers).
SHED_TIMEOUT = "timeout"
#: Abandoned: every engine of every retry of the recovery ladder failed.
SHED_DISPATCH = "dispatch-failed"
#: Handed off: the ticket left THIS worker's books for another fleet
#: worker (wedged-worker re-home or a whole-bucket work steal). Not a
#: terminal outcome for the REQUEST — the router pairs every re-homed
#: shed with an adoption elsewhere, and the fleet books count the
#: request once, at its final owner.
SHED_REHOMED = "re-homed"

SHED_REASONS = (SHED_DEPTH, SHED_PADDING, SHED_TIMEOUT, SHED_DISPATCH,
                SHED_REHOMED)


@dataclasses.dataclass(frozen=True)
class ServePolicy:
    """The serving daemon's knobs, one immutable bundle.

    ``max_wait_s`` is the padding-vs-latency trade: a bucket that never
    fills to ``max_batch`` still flushes once its oldest ticket has
    waited this long, bounding p99 at the cost of a padded dispatch.
    ``request_timeout_s`` is the end-to-end budget per ticket; the
    retry/backoff ladder never sleeps past it. Backoff is the
    ``robust.watchdog`` capped-exponential schedule with seeded jitter
    (thundering-herd guard when a queue loop requeues several daemons at
    once).
    """

    max_batch: int = 8
    max_depth: int = 64
    max_padding_frac: float = 0.375
    max_wait_s: float = 0.05
    request_timeout_s: float = 30.0
    max_retries: int = 2
    backoff_base_s: float = 0.05
    backoff_cap_s: float = 1.0
    backoff_jitter: float = 0.5
    seed: int = 0

    def __post_init__(self):
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {self.max_batch}")
        if self.max_depth < 1:
            raise ValueError(f"max_depth must be >= 1, got {self.max_depth}")
        if not 0.0 <= self.max_padding_frac <= 1.0:
            raise ValueError(
                f"max_padding_frac must be in [0, 1], got "
                f"{self.max_padding_frac}")
        for name in ("max_wait_s", "request_timeout_s", "backoff_base_s",
                     "backoff_cap_s"):
            if getattr(self, name) < 0:
                raise ValueError(f"{name} must be >= 0")


def padding_waste(
    bucket_counts: Iterable[int | tuple[int, int | None]],
    max_batch: int,
) -> float:
    """Estimated dead-padding fraction of dispatching these buckets now.

    Each bucket of ``r`` live requests dispatches as full ``max_batch``
    chunks plus one remainder chunk padded by
    ``serve.batcher.bucket_batch_size``; the waste is padded slots minus
    live requests over padded slots. 0.0 for an empty queue (nothing to
    dispatch wastes nothing).

    Items may be plain counts or ``(count, slice_width)`` pairs — the
    width the dispatcher will ACTUALLY pad that bucket's shape with
    (``ops.pallas_life.batch_slice_width``: 32 for bitsliced-eligible
    shapes, ``None`` for the pow2 ladder). Admission must project with
    the same width the dispatcher rounds with, or tickets get shed
    against the wrong denominator. For a width bucket that denominator
    is the PLANE, not the board slot: the board-sliced engine's cost
    unit is one plane of vector work, a partly-dead plane costs exactly
    what a full one does, and ``ceil(r/width)`` planes is already the
    minimum any dispatch of ``r`` such requests can pay — so plane
    padding is not avoidable waste, and the bucket counts as its plane
    quanta, fully live. (Counting dead board SLOTS here was the cliff
    this rule replaces: request 9 of a 64² bucket projected 72% "waste"
    and was shed, while its true marginal cost was zero.) Pow2 buckets
    keep the historical board-slot math — there the padded boards each
    cost real vmapped compute."""
    live = padded = 0
    for item in bucket_counts:
        r, width = item if isinstance(item, tuple) else (item, None)
        if r <= 0:
            continue
        full, rest = divmod(r, max_batch)
        if width and width <= max_batch:
            boards = full * max_batch
            if rest:
                boards += bucket_batch_size(rest, max_batch,
                                            slice_width=width)
            quanta = -(-boards // width)
            live += quanta
            padded += quanta
            continue
        live += r
        padded += full * max_batch
        if rest:
            padded += bucket_batch_size(rest, max_batch, slice_width=width)
    if padded == 0:
        return 0.0
    return (padded - live) / padded


def admit(policy: ServePolicy, depth: int,
          bucket_counts_after: Iterable[int | tuple[int, int | None]],
          ) -> str | None:
    """Admission verdict for one candidate request: ``None`` to accept,
    else the shed reason. ``depth`` is the pending count BEFORE the
    candidate; ``bucket_counts_after`` are per-bucket pending counts
    WITH the candidate already placed in its bucket — plain counts or
    ``(count, slice_width)`` pairs, as :func:`padding_waste` takes."""
    if depth >= policy.max_depth:
        return SHED_DEPTH
    if padding_waste(bucket_counts_after,
                     policy.max_batch) > policy.max_padding_frac:
        return SHED_PADDING
    return None


def rollup(policies: Iterable[ServePolicy]) -> ServePolicy:
    """One fleet-wide admission projection over per-worker budgets — the
    policy the router's door gate judges against BEFORE a request is
    routed to its affinity worker.

    Capacity budgets ADD across the fleet (``max_depth``: N workers
    drain N queues concurrently) while every per-request knob takes the
    most conservative worker's value (``max_padding_frac``, deadlines,
    timeouts, retries): the door must never promise latitude some shard
    cannot honor, or a hot shard wedges on work the fleet as a whole
    "had room" for. ``max_batch`` takes the max — padding-waste
    projection at the door needs the coarsest chunk quantum any worker
    will actually pad with. Raises ``ValueError`` on an empty fleet."""
    ps = list(policies)
    if not ps:
        raise ValueError("rollup: need at least one worker policy")
    return ServePolicy(
        max_batch=max(p.max_batch for p in ps),
        max_depth=sum(p.max_depth for p in ps),
        max_padding_frac=min(p.max_padding_frac for p in ps),
        max_wait_s=min(p.max_wait_s for p in ps),
        request_timeout_s=min(p.request_timeout_s for p in ps),
        max_retries=min(p.max_retries for p in ps),
        backoff_base_s=min(p.backoff_base_s for p in ps),
        backoff_cap_s=min(p.backoff_cap_s for p in ps),
        backoff_jitter=ps[0].backoff_jitter,
        seed=ps[0].seed,
    )


@dataclasses.dataclass(frozen=True)
class ElasticityPolicy:
    """Knobs for the SLO-driven scaling loop, one immutable bundle.

    The loop judges a live ``(p99, goodput/offered, depth)`` signal
    against a declared SLO and decides ``add`` / ``drain`` / nothing.
    Hysteresis is structural, not tuned-by-hope: an action needs
    ``breach_k`` (or ``surplus_k``) CONSECUTIVE observations on the
    same side, and after any action the controller holds still for
    ``cooldown_k`` observations — a signal oscillating inside one
    window can never flap the fleet, because neither streak completes.

    ``surplus_p99_frac``/``surplus_depth`` define "provably idle":
    scale-down needs the tail comfortably under SLO AND an (almost)
    empty fleet-wide queue — draining a worker that still holds depth
    would trade capacity for migration traffic at the worst moment.
    """

    slo_p99_s: float = 0.25
    slo_goodput_frac: float = 0.9
    min_workers: int = 1
    max_workers: int = 8
    breach_k: int = 3
    surplus_k: int = 6
    cooldown_k: int = 4
    surplus_p99_frac: float = 0.5
    surplus_depth: int = 0

    def __post_init__(self):
        if self.slo_p99_s <= 0:
            raise ValueError(
                f"slo_p99_s must be > 0, got {self.slo_p99_s}")
        if not 0.0 < self.slo_goodput_frac <= 1.0:
            raise ValueError(
                f"slo_goodput_frac must be in (0, 1], got "
                f"{self.slo_goodput_frac}")
        if self.min_workers < 1:
            raise ValueError(
                f"min_workers must be >= 1, got {self.min_workers}")
        if self.max_workers < self.min_workers:
            raise ValueError(
                f"max_workers ({self.max_workers}) must be >= "
                f"min_workers ({self.min_workers})")
        for name in ("breach_k", "surplus_k"):
            if getattr(self, name) < 1:
                raise ValueError(f"{name} must be >= 1")
        if self.cooldown_k < 0:
            raise ValueError(
                f"cooldown_k must be >= 0, got {self.cooldown_k}")
        if not 0.0 <= self.surplus_p99_frac < 1.0:
            raise ValueError(
                f"surplus_p99_frac must be in [0, 1), got "
                f"{self.surplus_p99_frac}")


#: Controller verdicts (:meth:`ElasticController.observe`).
SCALE_ADD = "add"
SCALE_DRAIN = "drain"


class ElasticController:
    """Pure hysteresis state machine over the elasticity policy.

    Clock-free and IO-free like everything else in this module: the
    fleet loop feeds it one observation per evaluation window and acts
    on the verdict; unit tests feed it synthetic signals and assert it
    cannot flap. ``observe`` returns :data:`SCALE_ADD`,
    :data:`SCALE_DRAIN`, or ``None``.
    """

    def __init__(self, policy: ElasticityPolicy | None = None):
        self.policy = policy or ElasticityPolicy()
        self.breach_streak = 0
        self.surplus_streak = 0
        self.cooldown = 0
        self.actions: list[str] = []

    def observe(self, *, p99_s: float, depth: int, workers: int,
                goodput_rps: float | None = None,
                offered_rps: float | None = None) -> str | None:
        """Judge one evaluation window. ``p99_s`` is the live tail over
        the window (0.0 = nothing resolved, which counts as a breach
        only when work was offered), ``depth`` the fleet-wide pending
        count, ``workers`` the current live worker count."""
        pol = self.policy
        starved = bool(offered_rps) and not goodput_rps
        breach = p99_s > pol.slo_p99_s or starved
        if (goodput_rps is not None and offered_rps is not None
                and offered_rps > 0):
            breach = breach or (goodput_rps
                                < pol.slo_goodput_frac * offered_rps)
        surplus = (p99_s < pol.surplus_p99_frac * pol.slo_p99_s
                   and depth <= pol.surplus_depth and not starved)
        if breach:
            self.breach_streak += 1
            self.surplus_streak = 0
        elif surplus:
            self.surplus_streak += 1
            self.breach_streak = 0
        else:
            self.breach_streak = 0
            self.surplus_streak = 0
        if self.cooldown > 0:
            self.cooldown -= 1
            return None
        if (self.breach_streak >= pol.breach_k
                and workers < pol.max_workers):
            return self._acted(SCALE_ADD)
        if (self.surplus_streak >= pol.surplus_k
                and workers > pol.min_workers):
            return self._acted(SCALE_DRAIN)
        return None

    def _acted(self, verdict: str) -> str:
        self.actions.append(verdict)
        self.breach_streak = 0
        self.surplus_streak = 0
        self.cooldown = self.policy.cooldown_k
        return verdict


def percentile(values: list[float], q: float) -> float:
    """Nearest-rank percentile (``q`` in [0, 100]) — the p50/p99 the
    bench line publishes. 0.0 on an empty list so a fully-shed run still
    renders a line."""
    if not values:
        return 0.0
    xs = sorted(values)
    if q <= 0:
        return xs[0]
    idx = max(0, min(len(xs) - 1, int(-(-q * len(xs) // 100)) - 1))
    return xs[idx]
