"""Shape-bucketed micro-batch dispatcher for stencil boards.

Buckets key on ``(shape, dtype, workload)`` — life rides the native
bit-packed batch engines, every other registered ``stencils`` workload
dispatches through the spec-generated vmapped roll engine.

See the package docstring for the serving model. The implementation is
deliberately host-side and synchronous — a queue of submitted boards,
one :meth:`ShapeBucketBatcher.flush` draining it bucket by bucket —
because the expensive resource being managed is DISPATCHES, not
threads: one flush turns R same-shape requests into
``ceil(R / max_batch)`` device programs instead of R.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax.numpy as jnp
import numpy as np

_BATCH_FNS = (
    "life_batch_bitsliced",
    "life_batch_vmem",
    "life_batch_xla",
    "life_batch_fused",
    "life_batch_frame",
    "pool_step",
)


def bucket_batch_size(
    n_requests: int, max_batch: int, slice_width: int | None = None
) -> int:
    """The padded batch a dispatch of ``n_requests`` same-shape boards
    uses: the next power of two, capped at ``max_batch``. The cap keeps
    the compiled-program set to at most ``log2(max_batch)+1`` stack
    shapes per board shape; the pow-2 rounding means a bucket that grows
    request by request re-compiles O(log R) times, not O(R).

    ``slice_width`` (``ops.pallas_life.batch_slice_width``) switches the
    rounding to plane multiples when the shape is bitsliced-eligible: a
    bitsliced dispatch costs the same for EVERY live count within a
    32-board plane, so a 20-request bucket pads straight to 32 (filling
    one plane) instead of wandering the pow2 ladder — fewer compiled
    stack shapes, never more planes of vector work (65 requests pad to
    96, not pow2's 128), and zero marginal compute for the padding.
    Chunks below ``BITSLICE_MIN_BATCH`` keep the pow2 rule: their
    padded stack would dispatch cell-packed anyway, and plane-rounding
    a lone request to 32 would make admission's waste projection shed
    the first submission to an empty queue. Also falls back to pow2
    when the width exceeds ``max_batch`` (the plane can never dispatch
    whole)."""
    if n_requests < 1:
        raise ValueError(f"bucket_batch_size: need >= 1 request, got {n_requests}")
    if slice_width and slice_width <= max_batch:
        from mpi_and_open_mp_tpu.ops.pallas_life import BITSLICE_MIN_BATCH

        if n_requests >= BITSLICE_MIN_BATCH:
            padded = -(-n_requests // slice_width) * slice_width
            if padded <= max_batch:
                return padded
    b = 1
    while b < n_requests and b < max_batch:
        b *= 2
    return min(b, max_batch)


def retrace_counts() -> dict[str, int]:
    """Compile counts per batched engine since the last
    ``obs.metrics.reset()`` — the bucketing verification: after a flush
    over K shape buckets (one padded size each), the values here sum to
    K. Zero-valued engines are omitted, matching the metrics registry."""
    from mpi_and_open_mp_tpu.obs import metrics

    out = {}
    for fn in _BATCH_FNS:
        n = metrics.get("jit.retrace", fn=fn)
        if n:
            out[fn] = int(n)
    return out


@dataclass
class _Request:
    ticket: int
    board: np.ndarray
    steps: int
    workload: str = "life"


@dataclass
class _BatchStat:
    """One dispatched device program, as reported by ``last_flush_stats``."""

    shape: tuple[int, int]
    steps: int
    requests: int
    padded_batch: int
    path: str
    tickets: tuple[int, ...] = field(default_factory=tuple)


class ShapeBucketBatcher:
    """Collect independent Life requests; flush them in shape buckets.

    ``submit(board, steps)`` enqueues a 2D board and returns a ticket;
    ``flush()`` advances everything queued and returns the results in
    SUBMISSION order (ticket order), one host array per request. Boards
    bucket by ``(shape, dtype)``; inside a bucket, requests with the
    same step count share a dispatch (different step counts need
    separate dispatches — all boards in a stack advance together — but
    still share the compiled program, steps being a runtime scalar).

    Every dispatch emits a ``serve.batch`` trace span (shape, steps,
    live/padded batch, native path) and ticks ``serve.requests`` /
    ``serve.batches`` / ``serve.padding`` metrics, so a bench or a CI
    run can audit exactly how many programs served how many requests.
    """

    def __init__(self, max_batch: int = 8, pool=None):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.max_batch = int(max_batch)
        self._queue: list[_Request] = []
        # Resident-session step requests, keyed for slab-group
        # coalescing: sessions whose lanes share a slab ride ONE
        # in-place masked dispatch even below BITSLICE_MIN_BATCH — the
        # mask is runtime data, so a lone lane and 32 slab-mates are the
        # same compiled program (``jit.retrace{fn=pool_step}``).
        self._pool = pool
        self._session_queue: list[tuple[int, str, int]] = []
        self._next_ticket = 0
        self.last_flush_stats: list[_BatchStat] = []

    def __len__(self) -> int:
        return len(self._queue) + len(self._session_queue)

    def submit(self, board: np.ndarray, steps: int,
               workload: str = "life") -> int:
        """Enqueue one board for ``steps`` stencil steps under
        ``workload`` (a registered ``stencils`` name, default life);
        returns a ticket (the request's index in the next flush's
        result list)."""
        from mpi_and_open_mp_tpu import stencils

        try:
            spec = stencils.get(workload)
        except KeyError as e:
            raise ValueError(str(e)) from None
        board = np.asarray(board)
        if (board.ndim < 2
                or board.shape != spec.board_shape(*board.shape[-2:])):
            want = ("3D (channels, ny, nx)" if spec.channels > 1
                    else "2D (ny, nx)")
            raise ValueError(
                f"submit: workload {workload!r} wants one {want} board "
                f"per request, got shape {board.shape}"
                " (stacks are the ENGINE layout; the batcher builds them)")
        steps = int(steps)
        if steps < 0:
            raise ValueError(f"submit: steps must be >= 0, got {steps}")
        ticket = self._next_ticket
        self._next_ticket += 1
        self._queue.append(_Request(ticket, board, steps, str(workload)))
        return ticket

    def submit_session(self, session: str, steps: int) -> int:
        """Enqueue one resident-session step (requires a ``pool``).
        Returns a ticket like :meth:`submit`; the flush result for a
        resident step is ``None`` — the board stays on device, that
        being the point."""
        if self._pool is None:
            raise ValueError(
                "submit_session: this batcher has no session pool")
        steps = int(steps)
        if steps < 0:
            raise ValueError(
                f"submit_session: steps must be >= 0, got {steps}")
        if not self._pool.has(str(session)):
            raise ValueError(
                f"submit_session: unknown session {session!r}")
        ticket = self._next_ticket
        self._next_ticket += 1
        self._session_queue.append((ticket, str(session), steps))
        return ticket

    def bucket_keys(self) -> list[tuple]:
        """The distinct buckets currently queued, in first-submission
        order: ``(shape, dtype, workload)`` for board requests,
        ``("slab", slab_id, steps)`` for resident-session steps
        (sessions sharing a slab and step count coalesce into one
        in-place dispatch)."""
        seen: dict[tuple, None] = {}
        for r in self._queue:
            seen.setdefault(
                (r.board.shape, r.board.dtype.str, r.workload), None)
        for _, sid, steps in self._session_queue:
            h = self._pool.handle(sid)
            slab = -1 if h is None else h.slab  # spilled: placed at flush
            seen.setdefault(("slab", slab, steps), None)
        return list(seen)

    def flush(self) -> list[np.ndarray]:
        """Advance every queued request; results in submission order."""
        from mpi_and_open_mp_tpu.obs import metrics, trace
        from mpi_and_open_mp_tpu.ops import pallas_life

        import jax

        results: dict[int, np.ndarray] = {}
        stats: list[_BatchStat] = []
        on_tpu = jax.default_backend() == "tpu"

        # Bucket by (shape, dtype, workload), sub-group by steps, chunk
        # at max_batch. Grouping is order-preserving within a bucket so
        # the span/ticket bookkeeping reads naturally in traces. A heat
        # board and a life board of the same shape never share a stack.
        buckets: dict[tuple, list[_Request]] = {}
        for r in self._queue:
            buckets.setdefault(
                (r.board.shape, r.board.dtype.str, r.workload), []).append(r)

        for (shape, _dtype, workload), reqs in buckets.items():
            by_steps: dict[int, list[_Request]] = {}
            for r in reqs:
                by_steps.setdefault(r.steps, []).append(r)
            # Bit-plane slice rounding is a Life binary-board layout;
            # other stencil workloads pad on the plain pow2 ladder.
            width = (pallas_life.batch_slice_width(shape, on_tpu=on_tpu)
                     if workload == "life" else None)
            for steps, group in by_steps.items():
                for lo in range(0, len(group), self.max_batch):
                    chunk = group[lo:lo + self.max_batch]
                    padded = bucket_batch_size(
                        len(chunk), self.max_batch, slice_width=width)
                    stack = np.zeros((padded, *shape), dtype=chunk[0].board.dtype)
                    for i, r in enumerate(chunk):
                        stack[i] = r.board
                    if workload == "life":
                        path = pallas_life.native_path_batch(
                            stack.shape, on_tpu=on_tpu)
                    else:
                        path = f"stencil:{workload}"
                    with trace.span(
                        "serve.batch", shape=f"{shape[-2]}x{shape[-1]}",
                        steps=steps, requests=len(chunk), padded=padded,
                        path=path, workload=workload,
                    ) as sp:
                        if workload == "life":
                            out = pallas_life.life_run_vmem_batch(
                                jnp.asarray(stack), steps)
                        else:
                            from mpi_and_open_mp_tpu import stencils

                            out = stencils.run_roll_batch(
                                stencils.get(workload), jnp.asarray(stack),
                                steps)
                        sp.anchor(out)
                    host = np.asarray(out)[: len(chunk)]
                    for i, r in enumerate(chunk):
                        results[r.ticket] = host[i]
                    metrics.inc("serve.requests", len(chunk))
                    metrics.inc("serve.batches")
                    if padded > len(chunk):
                        metrics.inc("serve.padding", padded - len(chunk))
                    stats.append(_BatchStat(
                        shape=shape, steps=steps, requests=len(chunk),
                        padded_batch=padded, path=path,
                        tickets=tuple(r.ticket for r in chunk)))

        # Resident-session steps: group by (current slab, steps) — each
        # slab-group is ONE donated masked dispatch regardless of how
        # few lanes are live (no BITSLICE_MIN_BATCH floor: the plane is
        # already resident, a lone lane costs the same vector work).
        session_tickets: list[int] = []
        if self._session_queue:
            groups: dict[tuple, list[tuple[int, str]]] = {}
            for ticket, sid, steps in self._session_queue:
                h = self._pool.handle(sid)
                slab = -1 if h is None else h.slab
                groups.setdefault((slab, steps), []).append((ticket, sid))
                session_tickets.append(ticket)
            for (slab, steps), members in groups.items():
                sids = [sid for _, sid in members]
                with trace.span("serve.batch", slab=slab, steps=steps,
                                requests=len(sids), path="pool"):
                    self._pool.step_group(sids, steps)
                for ticket, _ in members:
                    results[ticket] = None
                metrics.inc("serve.requests", len(sids))
                metrics.inc("serve.batches")
                stats.append(_BatchStat(
                    shape=("slab", slab), steps=steps,
                    requests=len(sids), padded_batch=len(sids),
                    path="pool", tickets=tuple(t for t, _ in members)))

        order = sorted([r.ticket for r in self._queue] + session_tickets)
        ordered = [results[t] for t in order]
        self._queue.clear()
        self._session_queue.clear()
        self.last_flush_stats = stats
        return ordered
