"""Supervised serving daemon: deadline scheduling + recovery ladder.

The always-on layer over the ``serve`` batching library. The reference's
serving story is one workload per ``mpirun`` launch and a PBS queue that
requeues the whole job on any failure; here a single process keeps a
bounded admission-controlled queue (``serve.queue``), flushes shape
buckets when they fill OR when their oldest ticket hits the policy
deadline (padding waste traded against p99 — a bucket that never fills
still flushes at ``max_wait_s``), and wraps every batch dispatch in a
supervision envelope so one poisoned request or wedged engine cannot
take the process down:

* **Engine ladder** — ``robust.guards.with_fallback`` over the batched
  native path → the vmapped XLA path → the NumPy oracle; a self-healed
  dispatch carries the ``:recovered`` provenance suffix on every ticket
  it resolved and lands in the process recovery log (``bench.py``
  publishes it — a silently degraded batch would launder a fault into a
  clean-looking artifact).
* **Bounded retry** — a full-ladder failure retries behind the
  ``robust.watchdog`` capped-exponential backoff with seeded jitter,
  never past ``max_retries`` or any member ticket's end-to-end timeout;
  exhaustion sheds the chunk with an explicit reason instead of looping.
* **Preemption** — SIGTERM/SIGINT land as a flag checked between batch
  dispatches (``robust.preempt``): the in-flight batch completes, the
  pending queue snapshots through the crash-atomic CRC state checkpoint
  (``utils.checkpoint.save_state``), and :class:`Preempted` propagates so
  drivers exit 75 (EX_TEMPFAIL) for the ``tpu_queue_loop.sh`` requeue;
  ``--resume`` restores every drained ticket, so an admitted request is
  never silently dropped. ``MOMP_CHAOS preempt=<k>`` rehearses the same
  path after ``k`` dispatched batches, and ``serve_fail=<k>`` drives the
  ladder mid-queue.
* **Hard-kill durability** — the drain checkpoint only exists if the
  process got to write it; a ``kill -9``/OOM/node loss never runs that
  code. With ``wal_path`` set, every ticket transition is journaled
  through the write-ahead log (``serve.wal``) *before* the daemon acts
  on it — admit, dispatch-begin, resolve, shed — under the
  policy-selectable fsync ladder, so :meth:`ServingDaemon.resume_any`
  can reconstruct the exact pending set (plus any in-flight batch, re-
  dispatched idempotently — dispatch is pure) from a process that died
  at an *arbitrary* instruction. Resume ladder: WAL snapshot+tail →
  drain checkpoint → fresh. ``MOMP_CHAOS crash=<site>:<k>`` hard-kills
  at the instrumented sites so the loss bound is proved, not assumed.

Every admission, shed, retry, degrade, and drain decision emits ``obs``
spans/events and metrics (``serve.*``), so a bench line or a CI soak can
assert the full accounting: requests == resolved + shed, always.
"""

from __future__ import annotations

import argparse
import collections
import contextlib
import json
import os
import sys
import time

import numpy as np

from mpi_and_open_mp_tpu.robust import chaos, guards, watchdog
from mpi_and_open_mp_tpu.robust.preempt import (
    EXIT_PREEMPTED, Preempted, SimulatedPreemption, flush_on_signal)
from mpi_and_open_mp_tpu.serve import policy as policy_mod
from mpi_and_open_mp_tpu.serve import wal as wal_mod
from mpi_and_open_mp_tpu.serve.batcher import bucket_batch_size
from mpi_and_open_mp_tpu.serve.policy import ServePolicy, percentile
from mpi_and_open_mp_tpu.serve.queue import (
    DONE, PENDING, SHED, ServeQueue, Ticket)
from mpi_and_open_mp_tpu.utils import checkpoint as checkpoint_mod


class ServingDaemon:
    """One supervised worker loop over a :class:`ServeQueue`.

    ``clock``/``sleep`` are injectable (tests drive deadlines and backoff
    without wall time); the default clock is monotonic — ticket
    timestamps never cross a process boundary raw (the checkpoint
    restores them against the resuming process's clock).
    """

    def __init__(self, policy: ServePolicy | None = None, *,
                 checkpoint_path: str | None = None,
                 wal_path: str | None = None,
                 wal_fsync: str = "every-record",
                 wal_compact_bytes: int = 1 << 20,
                 aot_cache=None,
                 plan_store=None,
                 worker_index: int | None = None,
                 pool_budget_bytes: int | None = None,
                 clock=time.monotonic, sleep=time.sleep):
        self.policy = policy or ServePolicy()
        # Fleet identity: which shard of a serve.fleet this process is.
        # None for the classic single-daemon deployment; the chaos
        # kill_worker=<i>:<k> drill targets exactly one index.
        self.worker_index = worker_index
        self.queue = ServeQueue(self.policy)
        self.checkpoint_path = checkpoint_path
        self._clock = clock
        self._sleep = sleep
        self._batches = 0
        self._retries = 0
        self._degraded = 0
        # Durable program store (serve.aotcache.AOTCache) — when set, the
        # dispatch ladder gets an `aot:*` top rung and resume preloads the
        # bucket executables, so the first restored ticket never waits on
        # a trace+compile. None = every dispatch traces as before.
        self._aot = aot_cache
        # Durable tuned-plan store (tune.plans.PlanStore) — installed at
        # construction so EVERY resume rung (wal/checkpoint/fresh) comes
        # up with plans steering native_path_batch before the first
        # dispatch, exactly as the AOT preload warms executables. None =
        # heuristics only, the historical behavior.
        self._plans = plan_store
        self._plans_summary = (plan_store.install()
                               if plan_store is not None else None)
        self._created_at = self._clock()
        self._first_result_s: float | None = None  # cold-start latency
        # The journal's "one chunk" loss bound under every-chunk is
        # literal: the buffer never holds more records than one dispatch
        # batch admits.
        self._wal = (wal_mod.TicketWAL(
            wal_path, fsync=wal_fsync,
            chunk_records=self.policy.max_batch,
            compact_bytes=wal_compact_bytes)
            if wal_path else None)
        # Device-resident session pool (serve.pool.SessionPool), built
        # lazily on the first create_session — single-shot burst daemons
        # never pay for it. `_session_log` is the HOST mirror of the
        # journal's view of every live session ({id, board, steps,
        # wall}): compaction snapshots it without touching the device,
        # and resume re-materializes into both the log and the pool.
        self._pool = None
        self._pool_budget = pool_budget_bytes
        self._session_log: dict[str, dict] = {}

    # -- intake ------------------------------------------------------------

    def submit(self, board: np.ndarray, steps: int,
               session: str | None = None,
               workload: str = "life") -> Ticket:
        """Admit (or reject-with-reason) one request; see
        :meth:`ServeQueue.submit`. An ADMITTED ticket is journaled before
        this returns — under ``every-record`` fsync the caller's ack
        implies durability (the crash-matrix's zero-acked-loss bound).
        Door-shed tickets are terminal before they exist anywhere worth
        replaying, so they never touch the journal. ``session`` is the
        fleet affinity key; it rides the journal so a router can re-home
        a dead worker's pending set by consistent hash. ``workload``
        names the stencil rule (``stencils.get``) — it buckets the
        dispatch, picks the engine ladder, and rides the journal."""
        t = self.queue.submit(board, steps, self._clock(), session=session,
                              workload=workload)
        if t.state == PENDING and self._wal is not None:
            # Instrumented crash site: admitted in memory, journal record
            # not yet written. A death here loses a ticket whose submit()
            # never returned — the caller was never acked, so the
            # zero-ACKED-loss bound is intact.
            if chaos.crash_armed("post-admit"):
                chaos.crash_now()
            self._wal.admit(t.id, t.board, t.steps, session=t.session,
                            workload=t.workload)
        return t

    # -- device-resident sessions -------------------------------------------

    @property
    def pool(self):
        """The device-resident session pool, built on first use."""
        if self._pool is None:
            from mpi_and_open_mp_tpu.serve.pool import SessionPool

            kw = {}
            if self._pool_budget is not None:
                kw["device_budget_bytes"] = self._pool_budget
            self._pool = SessionPool(**kw)
        return self._pool

    def create_session(self, session: str, board: np.ndarray):
        """Admit a board into the pool under ``session``. The board
        crosses the wire exactly once, here; the CREATE frame is durable
        before the device sees it, so kill -9 at any later instruction
        re-materializes the session from the journal. Returns the
        handle."""
        session = str(session)
        if session in self._session_log:
            raise ValueError(
                f"create_session: session {session!r} is already live")
        board = np.asarray(board)
        wall = time.time()
        if self._wal is not None:
            self._wal.pool_create(session, board, wall=wall)
            if chaos.crash_armed("post-create"):
                chaos.crash_now()
        handle = self.pool.create(session, board)
        self._session_log[session] = {
            "id": session, "board": board.copy(), "steps": 0, "wall": wall}
        return handle

    def step_session(self, session: str, steps: int) -> int:
        """Advance one resident session ``steps`` generations in place,
        synchronously (the ticketed fast path is
        :meth:`submit_session`). The STEP frame is write-ahead and
        authoritative: once this method returns, the advance survives
        any crash."""
        return self.step_sessions([str(session)], steps)

    def step_sessions(self, sessions: list[str], steps: int) -> int:
        steps = int(steps)
        for sid in sessions:
            if str(sid) not in self._session_log:
                raise ValueError(f"step_sessions: unknown session {sid!r}")
        if self._wal is not None:
            for sid in sessions:
                self._wal.pool_step(str(sid), steps)
            if chaos.crash_armed("post-step"):
                chaos.crash_now()
        n = self.pool.step_group([str(s) for s in sessions], steps)
        for sid in sessions:
            self._session_log[str(sid)]["steps"] += steps
        return n

    def submit_session(self, session: str, steps: int) -> Ticket:
        """Admit one resident step as a ticket — the handle-sized fast
        path. An admitted step journals exactly ONE frame (STEP, no
        ADMIT/DISPATCH/RESOLVE triple): write-ahead and authoritative,
        so the ack implied by this return is durable whether the
        dispatch happens in this process or is replayed into the pool
        on resume. Door-shed tickets never touch the journal."""
        session = str(session)
        if session not in self._session_log:
            raise ValueError(f"submit_session: unknown session {session!r}")
        t = self.queue.submit_session(
            session, self.pool.handle(session), steps, self._clock())
        if t.state == PENDING:
            if self._wal is not None:
                self._wal.pool_step(session, t.steps)
                if chaos.crash_armed("post-step"):
                    chaos.crash_now()
            self._session_log[session]["steps"] += t.steps
        return t

    def snapshot_session(self, session: str) -> np.ndarray:
        """Read a resident session's board (one device→host crossing).
        Parity contract: the returned board is bit-identical to the
        NumPy oracle advancing the create board by the journaled step
        total."""
        session = str(session)
        if session not in self._session_log:
            raise ValueError(
                f"snapshot_session: unknown session {session!r}")
        if self._wal is not None:
            self._wal.pool_snapshot(
                session, int(self._session_log[session]["steps"]))
            if chaos.crash_armed("post-snapshot"):
                chaos.crash_now()
        return self.pool.snapshot(session)

    def evict_session(self, session: str) -> np.ndarray:
        """Remove a session from the pool, returning its final board
        (the last wire crossing of the lifetime). The EVICT frame lands
        first, so a crash mid-evict replays to the evicted state rather
        than resurrecting the session."""
        session = str(session)
        if session not in self._session_log:
            raise ValueError(f"evict_session: unknown session {session!r}")
        if self._wal is not None:
            self._wal.pool_evict(session)
            if chaos.crash_armed("post-evict"):
                chaos.crash_now()
        board = self.pool.evict(session)
        del self._session_log[session]
        return board

    def adopt_session(self, session: str, board: np.ndarray,
                      steps: int):
        """The destination half of a pool re-home: journal a fresh
        CREATE + STEP lifetime on THIS worker's WAL, then let the
        device replay the advance (``board`` is the ORIGIN's create
        board; shipping it plus a step count moves one board across the
        wire instead of the whole history)."""
        session = str(session)
        board = np.asarray(board)
        steps = int(steps)
        wall = time.time()
        if self._wal is not None:
            self._wal.pool_create(session, board, wall=wall)
            if steps:
                self._wal.pool_step(session, steps)
            # Instrumented crash site: the destination half of a
            # membership handshake (rejoin adoption / drain migration)
            # is journaled, the SOURCE's EVICT frame is not — a kill
            # here leaves the session live in BOTH journals with
            # identical (create board, step total) resumable state:
            # duplicated, never lost, and bit-exact either way.
            if chaos.crash_armed("post-rejoin"):
                chaos.crash_now()
        handle = self.pool.create(session, board)
        if steps:
            self.pool.step(session, steps)
        self._session_log[session] = {
            "id": session, "board": board.copy(), "steps": steps,
            "wall": wall}
        return handle

    def sessions(self) -> list[str]:
        return list(self._session_log)

    def _rematerialize_pool(self, pool_sessions: dict[str, dict]) -> int:
        """Rebuild the device pool from a WAL replay's session map:
        every live session's create board enters the pool and advances
        by its journaled step total (a journaled-but-unacked step is
        applied — at-least-once on unacked work, zero acked loss)."""
        for sid, entry in pool_sessions.items():
            board = np.asarray(entry["board"])
            steps = int(entry["steps"])
            self.pool.create(sid, board)
            if steps:
                self.pool.step(sid, steps)
            self._session_log[sid] = {
                "id": sid, "board": board.copy(), "steps": steps,
                "wall": float(entry.get("wall", 0.0))}
        return len(pool_sessions)

    # -- fleet worker-mode hooks -------------------------------------------

    def release(self, tickets: list[Ticket],
                now: float | None = None) -> list[dict]:
        """Hand a group of PENDING tickets off this worker's books — the
        source half of a fleet re-home (wedged-worker drain) or a
        whole-bucket work steal. Each ticket sheds terminally here with
        the ``re-homed`` reason (journal frame first, so a later replay
        of THIS worker's WAL never re-dispatches work that now lives
        elsewhere) and comes back as a portable entry ``{board, steps,
        session, queued_s, wall}`` for :meth:`adopt` on the destination.
        Non-pending tickets are skipped — a result that already resolved
        must not be recomputed under a new id. Resident session tickets
        are skipped too: their STEP frames are already journaled and
        authoritative, so a pool re-home moves the SESSION (create board
        + step total, via :meth:`adopt_session`), never step tickets."""
        now = self._clock() if now is None else now
        live = [t for t in tickets
                if t.state == PENDING and t.board is not None]
        entries = self.export(live, now)
        self._shed_batch(live, policy_mod.SHED_REHOMED, now)
        return entries

    def export(self, tickets: list[Ticket],
               now: float | None = None) -> list[dict]:
        """Portable entries for a group of PENDING tickets WITHOUT
        closing this worker's books — the read half of :meth:`release`.
        A graceful drain adopts these at the destination FIRST and only
        then sheds them here: a crash between the halves leaves the
        bucket journaled at both workers (duplicated, re-dispatch is
        pure) instead of journaled at neither (lost). The wedge/steal
        path keeps the release-first order — there the source is
        already presumed dead and its journal replay is the source of
        truth."""
        now = self._clock() if now is None else now
        wall = time.time()
        return [
            {"board": np.asarray(t.board), "steps": t.steps,
             "session": t.session, "wall": wall,
             "workload": t.workload,
             "queued_s": t.queued_before_s + (now - t.submitted_at)}
            for t in tickets if t.state == PENDING and t.board is not None
        ]

    def adopt(self, entries: list[dict],
              now: float | None = None) -> list[Ticket]:
        """Admit re-homed/stolen entries (the destination half of
        :meth:`release`, and what the router feeds from a dead worker's
        WAL replay). No admission gate — the fleet already accepted this
        work once — and the carried ``queued_s``/``wall`` keep each
        ticket's end-to-end latency honest across the move. Adopted
        tickets are journaled like fresh admissions, so a crash of the
        ADOPTING worker re-homes them again instead of losing them."""
        now = self._clock() if now is None else now
        wall_now = time.time()
        out = []
        for e in entries:
            queued = float(e.get("queued_s", 0.0))
            wall = float(e.get("wall", 0.0))
            if wall:
                queued += max(0.0, wall_now - wall)
            t = self.queue.restore_ticket(
                e["board"], e["steps"], now, queued_s=queued,
                session=e.get("session"),
                workload=str(e.get("workload", "life")))
            if self._wal is not None:
                self._wal.admit(t.id, t.board, t.steps,
                                queued_s=queued, session=t.session,
                                workload=t.workload)
            out.append(t)
        return out

    @classmethod
    def resume(cls, checkpoint_path: str,
               policy: ServePolicy | None = None, **kw) -> "ServingDaemon":
        """A daemon whose queue starts from a drain checkpoint. Every
        pending ticket of the snapshot is re-admitted unconditionally
        (admission applies at the door, not to already-accepted work).
        Raises ``ValueError`` on a missing/corrupt/foreign checkpoint."""
        from mpi_and_open_mp_tpu.obs import trace

        state = checkpoint_mod.restore_state(checkpoint_path)
        daemon = cls(policy, checkpoint_path=checkpoint_path, **kw)
        restored = daemon.queue.restore(state, daemon._clock())
        trace.event("serve.resume", tickets=len(restored))
        if daemon._wal is not None:
            daemon._compact_wal()
        return daemon

    @classmethod
    def resume_any(cls, *, wal_path: str | None = None,
                   checkpoint_path: str | None = None,
                   policy: ServePolicy | None = None,
                   wal_fsync: str = "every-record",
                   **kw) -> tuple["ServingDaemon", str, dict]:
        """The resume ladder: WAL snapshot+tail → drain checkpoint →
        fresh. Returns ``(daemon, source, detail)`` where ``source`` is
        ``"wal"`` / ``"checkpoint"`` / ``"fresh"`` and ``detail`` carries
        replay accounting (and any swallowed ``wal_error``).

        The WAL rung survives deaths the checkpoint rung cannot: the
        drain checkpoint exists only if a polite signal handler got to
        run, while the journal was durable BEFORE the work happened. A
        WAL whose tail is torn replays to its last complete frame (loss
        bounded by the fsync policy); a WAL that is unreadable outright
        falls through to the checkpoint rung rather than refusing to
        serve. Tickets that were in-flight (DISPATCH without RESOLVE)
        come back pending — dispatch is pure, so redoing them is
        idempotent. After a WAL resume the journal is immediately
        compacted: the restored tickets carry NEW ids in this process,
        and rotation re-anchors the journal on them (also discarding any
        torn tail so fresh frames never sit behind garbage).

        Corrupt artifacts on EITHER durable rung quarantine to a
        generation-stamped ``.corrupt.<stamp>`` sibling
        (``utils.checkpoint.quarantine``) and the ladder falls through —
        a second corrupt resume gets its own forensic copy, never
        clobbering the first, and a rotten checkpoint degrades to a
        fresh daemon instead of a crash. With an ``aot_cache`` in
        ``**kw``, every rung ends in a preload phase: the bucket
        executables for the restored pending set deserialize (or build)
        BEFORE the first dispatch, so warm-resume p99 never eats a
        trace+compile (``detail["aot_preload"]``)."""
        from mpi_and_open_mp_tpu.obs import trace

        detail: dict = {}
        if wal_path and os.path.exists(wal_path):
            try:
                rep = wal_mod.replay(wal_path)
            except ValueError as e:
                detail["wal_error"] = str(e)[:300]
                trace.event("serve.resume.wal_error", error=str(e)[:200])
                # Quarantine the unreadable journal (forensics intact,
                # uniquely stamped): appending fresh frames behind a bad
                # head would poison every future replay too.
                q = checkpoint_mod.quarantine(wal_path)
                if q:
                    detail["wal_quarantine"] = q
            else:
                daemon = cls(policy, checkpoint_path=checkpoint_path,
                             wal_path=wal_path, wal_fsync=wal_fsync, **kw)
                daemon._wal._generation = rep.generation
                now = daemon._clock()
                wall_now = time.time()
                for entry in rep.pending:
                    queued = float(entry.get("queued_s", 0.0))
                    wall = float(entry.get("wall", 0.0))
                    if wall:
                        # Seconds the ticket sat in the DEAD process (and
                        # the gap until this restart) — wall clock is the
                        # only clock that crosses a process boundary.
                        queued += max(0.0, wall_now - wall)
                    daemon.queue.restore_ticket(
                        entry["board"], entry["steps"], now, queued_s=queued,
                        session=entry.get("session"),
                        workload=str(entry.get("workload", "life")))
                # Re-materialize the device pool BEFORE rotating the
                # journal: rotation snapshots the session log, so the
                # log must already hold every replayed session.
                if rep.pool_sessions:
                    daemon._rematerialize_pool(rep.pool_sessions)
                daemon._compact_wal()
                detail["wal_replay"] = rep.counts()
                trace.event("serve.resume", source="wal",
                            tickets=len(rep.pending))
                daemon._aot_preload(detail)
                daemon._plans_note(detail)
                return daemon, "wal", detail
        if checkpoint_path and os.path.exists(checkpoint_path):
            try:
                daemon = cls.resume(checkpoint_path, policy,
                                    wal_path=wal_path,
                                    wal_fsync=wal_fsync, **kw)
            except ValueError as e:
                # Same contract as the WAL rung: a corrupt/skewed drain
                # checkpoint is quarantined (stamped — the forensic copy
                # of an earlier corrupt resume survives) and the ladder
                # falls through to fresh rather than refusing to serve.
                detail["checkpoint_error"] = str(e)[:300]
                trace.event("serve.resume.checkpoint_error",
                            error=str(e)[:200])
                q = checkpoint_mod.quarantine(checkpoint_path)
                if q:
                    detail["checkpoint_quarantine"] = q
            else:
                daemon._aot_preload(detail)
                daemon._plans_note(detail)
                return daemon, "checkpoint", detail
        daemon = cls(policy, checkpoint_path=checkpoint_path,
                     wal_path=wal_path, wal_fsync=wal_fsync, **kw)
        trace.event("serve.resume", source="fresh", tickets=0)
        daemon._plans_note(detail)
        return daemon, "fresh", detail

    def _aot_preload(self, detail: dict | None = None) -> dict | None:
        """Warm the AOT cache for every (shape, dtype) currently pending:
        all power-of-two bucket programs up to ``max_batch`` are resident
        before the first dispatch. No-op without a cache or pending work;
        returns (and records in ``detail``) the warm-pass stats."""
        if self._aot is None:
            return None
        # The durable program store holds LIFE bucket executables only —
        # other stencil workloads trace per process (their rung ladder
        # has no aot top rung), so they contribute nothing to warm.
        boards = {(t.board.shape, str(np.asarray(t.board).dtype))
                  for t in self.queue.pending()
                  if t.board is not None and t.workload == "life"}
        if not boards:
            return None
        summary = self._aot.warm(sorted(boards), self.policy.max_batch)
        if detail is not None:
            detail["aot_preload"] = summary
        return summary

    def _plans_note(self, detail: dict | None = None) -> dict | None:
        """Record the plan-store install bookkeeping (done once, at
        construction) in the resume detail — an exit-75 requeue restarts
        with tuned plans AND their executables warm, observably."""
        if self._plans_summary is not None and detail is not None:
            detail["plans"] = self._plans_summary
        return self._plans_summary

    # -- the supervised loop ----------------------------------------------

    def serve(self, *, watch_signals: bool = True,
              idle_tick_s: float = 0.005) -> None:
        """Dispatch until every admitted ticket is terminal. Raises
        :class:`Preempted` (after checkpointing the queue) on SIGTERM/
        SIGINT or a chaos-plan preemption; anything else runs to a fully
        drained queue."""
        with flush_on_signal(watch_signals) as watch:
            while True:
                dispatched = self.pump(watch=watch)
                if not self.queue.pending():
                    if self._wal is not None:
                        self._wal.sync()
                    return
                if dispatched == 0:
                    self._check_interrupts(watch)
                    horizon = self.queue.next_deadline()
                    wait = idle_tick_s
                    if horizon is not None:
                        wait = max(1e-4, horizon - self._clock())
                    self._sleep(wait)

    def pump(self, now: float | None = None, *, drain: bool = False,
             watch=None) -> int:
        """Dispatch every currently-due chunk (all of them when
        ``drain``); returns the number of batches dispatched. Interrupt
        flags are honored BETWEEN chunk dispatches — an in-flight batch
        always completes (the drain half of the preemption contract)."""
        now = self._clock() if now is None else now
        n = 0
        for chunk in self.queue.due_chunks(now, drain=drain):
            self._check_interrupts(watch)
            self._dispatch_chunk(chunk)
            n += 1
        if self._wal is not None and self._wal.should_compact():
            self._compact_wal()
        if self._pool is not None:
            # Background lane hygiene: repack sparse planes left by dead
            # sessions while the queue is quiet — the device pays a
            # 32-at-a-time pack/unpack, never a per-lane shuffle.
            self._pool.maybe_compact()
        return n

    def drain(self) -> None:
        """Flush everything pending regardless of deadlines (shutdown
        path and tests)."""
        while self.queue.pending():
            self.pump(drain=True)

    # -- internals ---------------------------------------------------------

    def _compact_wal(self) -> None:
        """Rotate the journal around the CURRENT pending set: one
        crash-atomic snapshot (generation-stamped ``save_state`` file)
        plus a fresh WAL whose head frame points at it. Queued seconds
        are folded to now so a replay in a later process keeps the true
        end-to-end clock running."""
        now = self._clock()
        wall = time.time()
        entries = [
            {"id": t.id, "board": np.asarray(t.board), "steps": t.steps,
             "wall": wall, "session": t.session, "workload": t.workload,
             "queued_s": t.queued_before_s + (now - t.submitted_at)}
            for t in self.queue.pending() if t.board is not None
        ]
        self._wal.compact(entries, pool_sessions=self._session_log)

    def _shed_batch(self, tickets: list[Ticket], reason: str,
                    now: float) -> None:
        """Shed a group terminally, journal first — the SHED frame is
        what stops a replay from re-dispatching work the policy already
        refused (one frame for the group; the per-ticket accounting
        lives in the queue)."""
        if self._wal is not None and tickets:
            self._wal.shed([t.id for t in tickets], reason)
        for t in tickets:
            self.queue.shed_ticket(t, reason, now)

    def _check_interrupts(self, watch) -> None:
        if watch is not None and watch.fired is not None:
            self._preempt(signum=watch.fired)
        plan = chaos.active_plan()
        if (plan is not None and plan.preempt_pending(0)
                and self._batches >= plan.preempt_step):
            plan.preempt_fired = True
            self._preempt(simulated=True)

    def _preempt(self, signum: int | None = None,
                 simulated: bool = False) -> None:
        """Checkpoint the pending queue and stop. The drain decision is
        observable: a ``serve.drain`` event with the batch/pending counts
        and the checkpoint path rides the trace stream."""
        from mpi_and_open_mp_tpu.obs import metrics, trace

        path = None
        if self._wal is not None:
            self._wal.sync()
        if self.checkpoint_path:
            checkpoint_mod.save_state(
                self.checkpoint_path, self.queue.snapshot(self._clock()))
            path = self.checkpoint_path
        metrics.inc("serve.preempted")
        trace.event("serve.drain", batches=self._batches,
                    pending=self.queue.depth(), checkpoint=path or "")
        cls = SimulatedPreemption if simulated else Preempted
        raise cls(self._batches, checkpoint=path, signum=signum)

    def _validator(self, stack_shape: tuple, spec=None):
        """Sanity gate every rung's output passes before it resolves
        tickets. Life keeps the historic binary-board check; other
        stencil workloads validate through the spec's own invariant
        (state range for automata, finiteness for float fields)."""
        if spec is None or spec.name == "life":
            def ok(out) -> bool:
                a = np.asarray(out)
                return a.shape == stack_shape and bool((a <= 1).all())
        else:
            def ok(out) -> bool:
                a = np.asarray(out)
                return (a.shape == stack_shape
                        and all(spec.valid_board(b) for b in a))

        return ok

    def _engines(self, stack: np.ndarray, steps: int, spec=None):
        """The graceful-degradation ladder for one padded chunk, ranked:
        the durable AOT executable (when a cache is attached — a
        deserialized ``jax.export`` program that runs with ZERO
        retraces, oracle parity-gated on first use), then the batched
        native path — bitsliced board-planes when the stack qualifies,
        else the cell-packed ladder — then, under a bitsliced plan, the
        cell-packed native engine with the layout pinned off (a poisoned
        bitsliced dispatch degrades one rung, not straight to vmapped
        XLA), then the always-compilable vmapped XLA bit engine, then
        the NumPy oracle — the one engine that needs no device at all.
        The AOT rung's stamp carries its cache provenance:
        ``aot:<path>`` on a hit/resident program, ``aot:<path>:miss`` /
        ``aot:<path>:corrupt`` / ``aot:<path>:stale`` when this dispatch
        had to build fresh (a bad artifact was quarantined first).
        Fallback engines run under ``chaos.suppressed()`` so a recovery
        dispatch cannot be re-failed by the fault that triggered it.

        Non-life stencil workloads (``spec`` given and not life) get a
        two-rung ladder instead — the spec-generated vmapped roll engine
        (``batch:stencil:<name>``) over the spec's own NumPy oracle —
        because the bit-packed/bit-sliced machinery below is a Life
        binary-board specialization by construction."""
        import jax

        from mpi_and_open_mp_tpu.ops import bitlife, pallas_life

        if spec is not None and spec.name != "life":
            from mpi_and_open_mp_tpu import stencils

            def stencil_rung(runner, guarded: bool):
                def run():
                    import jax.numpy as jnp

                    if guarded and chaos.take_serve_fault():
                        raise RuntimeError(
                            "chaos: injected serve dispatch fault")
                    with (contextlib.nullcontext() if guarded
                          else chaos.suppressed()):
                        return np.asarray(
                            runner(jnp.asarray(stack), steps))
                return run

            def stencil_oracle():
                with chaos.suppressed():
                    out = np.array(stack, copy=True)
                    for b in range(out.shape[0]):
                        out[b] = stencils.oracle_run(spec, out[b], steps)
                    return out

            # Every legal engine for this spec, ladder order: the roll
            # engine leads, then the Pallas padded kernel
            # (single-channel specs), then the PR 20 engine families
            # where their legality gates + the MOMP_ENGINE_FAMILY pin
            # allow. An installed tuned plan promotes ITS rung to the
            # front (so the tuner's winner is exactly what serving
            # runs); the front rung is the guarded primary, the rest
            # are chaos-suppressed fallbacks, the oracle closes.
            avail = [(f"batch:stencil:{spec.name}", "stencil:roll",
                      lambda s, n: stencils.run_roll_batch(spec, s, n))]
            if stencils.pallas_batch_supported(spec, stack.shape):
                avail.append(
                    (f"batch:stencil-pallas:{spec.name}",
                     "stencil:pallas",
                     lambda s, n: stencils.run_padded_pallas_batch(
                         spec, s, n)))
            if (stencils.separable_supported(spec)
                    and stencils.family_allowed("sep")):
                avail.append(
                    (f"batch:stencil-sep:{spec.name}", "stencil:sep",
                     lambda s, n: stencils.run_family_batch(
                         spec, s, n, "sep")))
            if (stencils.fft_supported(spec)
                    and stencils.family_allowed("fft")):
                avail.append(
                    (f"batch:stencil-fft:{spec.name}", "stencil:fft",
                     lambda s, n: stencils.run_family_batch(
                         spec, s, n, "fft")))
            planned = pallas_life.planned_path(spec.name, stack.shape)
            avail.sort(key=lambda e: e[1] != planned)
            rungs = [(name, stencil_rung(runner, i == 0))
                     for i, (name, _, runner) in enumerate(avail)]
            return rungs + [("oracle", stencil_oracle)]

        on_tpu = jax.default_backend() == "tpu"
        path = pallas_life.native_path_batch(stack.shape, on_tpu=on_tpu)

        rungs = []
        if self._aot is not None:
            digest, exported, status = self._aot.ensure(
                stack.shape, stack.dtype)
            if exported is not None:
                stamp = (f"aot:{path}" if status in ("memory", "hit")
                         else f"aot:{path}:{status}")

                def aot():
                    if chaos.take_serve_fault():
                        raise RuntimeError(
                            "chaos: injected serve dispatch fault")
                    return self._aot.call_verified(digest, stack, steps)

                rungs.append((stamp, aot))

        def native():
            import jax.numpy as jnp

            if chaos.take_serve_fault():
                raise RuntimeError("chaos: injected serve dispatch fault")
            return np.asarray(
                pallas_life.life_run_vmem_batch(jnp.asarray(stack), steps))

        def xla():
            import jax.numpy as jnp

            with chaos.suppressed():
                return np.asarray(
                    bitlife.life_run_bits_xla_batch(jnp.asarray(stack),
                                                    steps))

        def oracle():
            from mpi_and_open_mp_tpu.ops.life_ops import life_step_numpy

            with chaos.suppressed():
                out = np.array(stack, copy=True)
                for b in range(out.shape[0]):
                    board = out[b]
                    for _ in range(steps):
                        board = life_step_numpy(board)
                    out[b] = board
                return out

        rungs.append((f"batch:{path}", native))
        if path == "bitsliced":
            # One-rung degrade: re-plan the same stack with the layout
            # pinned off. Off-TPU the cell-packed plan is "xla" already,
            # identical to the rung below — skip the duplicate.
            cp_path = pallas_life.native_path_batch(
                stack.shape, on_tpu=on_tpu, allow_bitsliced=False)
            if cp_path != "xla":

                def cellpacked():
                    import jax.numpy as jnp

                    with chaos.suppressed(), \
                            pallas_life._bitslice_pinned(False):
                        return np.asarray(pallas_life.life_run_vmem_batch(
                            jnp.asarray(stack), steps))

                rungs.append((f"batch:{cp_path}", cellpacked))
        rungs += [("batch:xla", xla), ("oracle", oracle)]
        return rungs

    def _dispatch_pool_chunk(self, chunk: list[Ticket]) -> None:
        """Resolve one slab-group of resident step tickets with a single
        in-place pool dispatch. No WAL frames here — each ticket's STEP
        frame was journaled (authoritative) at submit, so a death at any
        point in this method replays the advance into the pool on
        resume; no timeout shed either, for the same reason (the step is
        already promised durable, so it must happen exactly once)."""
        from mpi_and_open_mp_tpu.obs import metrics, trace

        steps = chunk[0].steps
        # Open-loop traffic can park TWO steps for the same session in
        # one bucket. `step_group` ORs each lane into the dispatch mask,
        # so duplicates collapse: the lane would advance `steps` once
        # while both tickets resolve. Split the chunk into waves of
        # distinct sessions and dispatch the waves in arrival order —
        # the all-distinct common case stays one dispatch.
        waves: list[list[Ticket]] = []
        for t in chunk:
            for wave in waves:
                if all(w.session != t.session for w in wave):
                    wave.append(t)
                    break
            else:
                waves.append([t])
        with trace.span("serve.dispatch.pool", requests=len(chunk),
                        steps=steps):
            for wave in waves:
                self.pool.step_group([t.session for t in wave], steps)
        now = self._clock()
        for t in chunk:
            self.queue.resolve(t, None, "pool:bitsliced", now)
        if self._first_result_s is None:
            self._first_result_s = now - self._created_at
        self._batches += 1
        metrics.inc("serve.batches")

    def _dispatch_chunk(self, chunk: list[Ticket]) -> None:
        from mpi_and_open_mp_tpu.obs import metrics, trace

        if chunk and chunk[0].handle is not None:
            self._dispatch_pool_chunk(chunk)
            return

        p = self.policy
        now = self._clock()
        # Per-request timeout, checked at the last instant before device
        # work: a ticket that already blew its end-to-end budget (earlier
        # retries, chaos delays, a starved bucket) sheds explicitly
        # instead of burning a dispatch whose answer nobody is waiting
        # for.
        live, stale = [], []
        for t in chunk:
            if now - t.submitted_at > p.request_timeout_s:
                stale.append(t)
            else:
                live.append(t)
        self._shed_batch(stale, policy_mod.SHED_TIMEOUT, now)
        if not live:
            return

        if self._wal is not None:
            # DISPATCH_BEGIN before any engine runs: a death between here
            # and the RESOLVE frame replays these tickets as pending (the
            # in-flight batch) and redispatches them — dispatch is pure,
            # so the redo is idempotent.
            self._wal.dispatch_begin([t.id for t in live])
        # Fleet chaos drill: kill_worker=<i>:<k> dies HERE, mid-dispatch
        # — the DISPATCH frame is journaled, no RESOLVE ever will be, so
        # the router's replay of this worker's WAL must surface the
        # chunk as in-flight and re-home it (dispatch is pure; redoing
        # it on a survivor is idempotent).
        if chaos.kill_worker_armed(self.worker_index):
            chaos.crash_now()
        from mpi_and_open_mp_tpu import stencils

        spec = stencils.get(live[0].workload)
        shape = live[0].board.shape
        steps = live[0].steps
        padded = bucket_batch_size(
            len(live), p.max_batch,
            slice_width=self.queue._slice_width(live[0].bucket_key))
        stack = np.zeros((padded, *shape), dtype=live[0].board.dtype)
        for i, t in enumerate(live):
            stack[i] = t.board
        # Life keeps the historic two-arg call (its ladder never needs
        # the spec); non-life workloads thread theirs through.
        if spec.name == "life":
            engines = self._engines(stack, steps)
        else:
            engines = self._engines(stack, steps, spec)
        validator = self._validator(stack.shape, spec)
        # One jittered backoff schedule per chunk, seeded off the lead
        # ticket so concurrent requeued daemons desynchronise while any
        # single run stays reproducible.
        waits = watchdog.backoff(p.backoff_base_s, p.backoff_cap_s,
                                 jitter=p.backoff_jitter,
                                 seed=p.seed + live[0].id)
        deadline = min(t.submitted_at for t in live) + p.request_timeout_s
        attempt = 0
        while True:
            delay = chaos.dispatch_delay()
            if delay:
                self._sleep(delay)
            try:
                with trace.span(
                    "serve.dispatch", shape=f"{shape[-2]}x{shape[-1]}",
                    steps=steps, requests=len(live), padded=padded,
                    workload=spec.name, attempt=attempt,
                ):
                    out, stamp, _notes = guards.with_fallback(
                        engines, validator=validator)
                break
            except guards.FallbackExhausted as e:
                attempt += 1
                self._retries += 1
                metrics.inc("serve.retries")
                trace.event("serve.retry", attempt=attempt,
                            notes="; ".join(e.notes)[:200])
                now = self._clock()
                if attempt > p.max_retries:
                    self._shed_batch(live, policy_mod.SHED_DISPATCH, now)
                    return
                wait = next(waits)
                if now + wait > deadline:
                    self._shed_batch(live, policy_mod.SHED_TIMEOUT, now)
                    return
                self._sleep(wait)

        if stamp.endswith(":recovered"):
            # The degrade decision, on the record: aggregate count +
            # ordered stamp in the process recovery log (what bench.py
            # publishes as `recovered`) + a trace event via the funnel.
            self._degraded += 1
            metrics.inc("serve.degraded")
            guards.record_recovery(f"serve:{stamp}")
        now = self._clock()
        host = np.asarray(out)[:len(live)]
        if self._wal is not None:
            # Instrumented crash site: batch computed, RESOLVE frame not
            # yet journaled. A death here replays the batch as in-flight
            # and the resumed daemon redoes it — results were never
            # surfaced, so redoing is the correct (idempotent) outcome.
            if chaos.crash_armed("post-dispatch"):
                chaos.crash_now()
            self._wal.resolve([t.id for t in live], engine=stamp)
        for i, t in enumerate(live):
            self.queue.resolve(t, host[i], stamp, now)
        if self._first_result_s is None:
            # Cold-start latency: daemon construction to the first
            # resolved result — the number the AOT cache exists to crush
            # (trace+compile lands here on a cold resume, pure
            # deserialization on a warm one).
            self._first_result_s = now - self._created_at
        self._batches += 1
        metrics.inc("serve.batches")
        if padded > len(live):
            metrics.inc("serve.padding", padded - len(live))

    # -- accounting --------------------------------------------------------

    def summary(self) -> dict:
        """The accounting the soak test and the bench line read: every
        ticket in exactly one terminal bucket, latency percentiles over
        the resolved set, engine/reason breakdowns."""
        tickets = self.queue.tickets()
        done = [t for t in tickets if t.state == DONE]
        shed = [t for t in tickets if t.state == SHED]
        lat = [t.latency_s for t in done]
        out = {
            "requests": len(tickets),
            "resolved": len(done),
            "shed": len(shed),
            "pending": self.queue.depth(),
            "batches": self._batches,
            "retries": self._retries,
            "degraded": self._degraded,
            "shed_reasons": dict(collections.Counter(
                t.reason for t in shed)),
            "engines": dict(collections.Counter(t.engine for t in done)),
            "p50_latency_s": round(percentile(lat, 50), 6),
            "p99_latency_s": round(percentile(lat, 99), 6),
        }
        if self._first_result_s is not None:
            out["cold_first_result_s"] = round(self._first_result_s, 6)
        if self._pool is not None:
            s = self._pool.stats()
            out["pool"] = s
            # Flat copies of the fields the bench line and the
            # regression sentinel watch.
            out["pool_sessions"] = s["sessions"]
            out["pool_hits"] = s["hits"]
            out["pool_misses"] = s["misses"]
            out["pool_evictions"] = s["evictions"]
            out["pool_spills"] = s["spills"]
            out["pool_compactions"] = s["compactions"]
            out["pool_settled_skips"] = s["settled_skips"]
        if self._wal is not None:
            out["wal"] = self._wal.stats()
        if self._aot is not None:
            s = self._aot.stats()
            out["aot"] = s
            # Flat copies of the fields the bench line and the
            # regression sentinel watch.
            out["aot_hits"] = s["hits"]
            out["aot_misses"] = s["misses"]
            out["aot_corrupt"] = s["corrupt"]
            out["aot_stale"] = s["stale"]
            out["aot_deserialize_s"] = s["deserialize_s"]
            out["aot_build_s"] = s["build_s"]
        if self._plans_summary is not None:
            out["plans"] = self._plans_summary
            out["plans_installed"] = self._plans_summary["installed"]
        return out


# -- CLI -------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mpi_and_open_mp_tpu.serve.daemon",
        description="Fault-tolerant Life serving daemon: submit a seeded "
        "mixed-shape burst, drain it under the supervision ladder, print "
        "ONE JSON summary line. SIGTERM checkpoints the queue and exits "
        "75 (EX_TEMPFAIL); --resume continues it.")
    p.add_argument("--requests", type=int, default=32, metavar="N",
                   help="burst size (default 32; 0 with --resume drains "
                   "the checkpoint only)")
    p.add_argument("--shapes", default="48x48,64x64", metavar="S",
                   help="comma-separated NYxNX request shapes, cycled "
                   "over the burst (default %(default)s)")
    p.add_argument("--steps", default="4,8", metavar="K",
                   help="comma-separated step counts, cycled (default "
                   "%(default)s)")
    p.add_argument("--workload", default="life", metavar="NAME",
                   help="stencil workload for the burst (a registered "
                   "stencils name: life, heat, gray_scott, wireworld; "
                   "default %(default)s) — boards come from the spec's "
                   "own seeder and dispatch through the spec's engine "
                   "ladder")
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--max-depth", type=int, default=4096)
    p.add_argument("--max-wait", type=float, default=0.02, metavar="S",
                   help="per-bucket deadline seconds (default 0.02)")
    p.add_argument("--timeout", type=float, default=60.0, metavar="S",
                   help="per-request end-to-end budget (default 60)")
    p.add_argument("--retries", type=int, default=2)
    p.add_argument("--max-padding-frac", type=float, default=0.375,
                   metavar="F",
                   help="admission budget for estimated dead-padding "
                   "fraction of the pending set (default %(default)s); "
                   "fleet workers run heterogeneous budgets through "
                   "this knob")
    p.add_argument("--backoff", default="0.05:1.0:0.5", metavar="B[:C[:J]]",
                   help="retry backoff schedule base[:cap[:jitter]] "
                   "seconds (default %(default)s) — the "
                   "capped-exponential ladder a full-ladder dispatch "
                   "failure retries behind")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--checkpoint", default=None, metavar="PATH",
                   help="queue drain checkpoint file (written on "
                   "SIGTERM/preemption)")
    p.add_argument("--wal", default=None, metavar="PATH",
                   help="write-ahead ticket journal: every transition is "
                   "durable BEFORE the daemon acts on it, so --resume "
                   "recovers from kill -9 at any instruction, not just "
                   "a polite SIGTERM drain")
    p.add_argument("--wal-fsync", default="every-record",
                   choices=list(wal_mod.FSYNC_POLICIES),
                   help="journal durability ladder: every-record = zero "
                   "acked loss on any death; every-chunk = at most one "
                   "batch of records on power cut; off = page-cache "
                   "only (still zero loss on process death; default "
                   "%(default)s)")
    p.add_argument("--aot-cache", default=None, metavar="DIR",
                   help="durable AOT executable cache directory (default "
                   "$MOMP_AOT_CACHE): bucket programs persist as "
                   "jax.export artifacts, so a restarted daemon "
                   "deserializes instead of re-tracing — warm resume "
                   "shows zero jit.retrace{fn=life_batch_*} ticks; a "
                   "corrupt/stale artifact quarantines and falls back "
                   "to a fresh trace (aot:corrupt provenance)")
    p.add_argument("--plans", default=None, metavar="DIR",
                   help="durable tuned-plan store directory (default "
                   "$MOMP_TUNE_PLANS; usually the SAME directory as "
                   "--aot-cache — plan and executable share one "
                   "fingerprint digest): momp-plan/1 records are "
                   "validated, parity-gated, and installed before the "
                   "first dispatch so native_path_batch follows the "
                   "measured winner; corrupt/stale/parity-failing "
                   "records quarantine and the heuristics serve "
                   "unchanged; MOMP_TUNE=0 ignores the store entirely")
    p.add_argument("--resume", action="store_true",
                   help="restore drained tickets before serving the "
                   "(possibly empty) new burst — WAL replay first, then "
                   "the drain checkpoint, then fresh (requires --wal "
                   "and/or --checkpoint)")
    p.add_argument("--verify", action="store_true",
                   help="gate every resolved board bit-exact against the "
                   "NumPy oracle before reporting (CI smoke)")
    return p


def _parse_backoff(spec: str) -> tuple[float, float, float]:
    """``base[:cap[:jitter]]`` → the three ServePolicy backoff numbers
    (missing fields keep the policy defaults)."""
    parts = [p for p in str(spec).split(":") if p != ""]
    if not 1 <= len(parts) <= 3:
        raise ValueError(
            f"--backoff wants base[:cap[:jitter]], got {spec!r}")
    base = float(parts[0])
    cap = float(parts[1]) if len(parts) > 1 else 1.0
    jitter = float(parts[2]) if len(parts) > 2 else 0.5
    return base, cap, jitter


def _parse_shapes(spec: str) -> list[tuple[int, int]]:
    shapes = []
    for tok in spec.split(","):
        ny, _, nx = tok.strip().partition("x")
        shapes.append((int(ny), int(nx)))
    return shapes


def _burst(daemon: ServingDaemon, args) -> None:
    from mpi_and_open_mp_tpu import stencils

    spec = stencils.get(args.workload)
    shapes = _parse_shapes(args.shapes)
    steps = [int(s) for s in args.steps.split(",")]
    rng = np.random.default_rng(args.seed)
    for i in range(args.requests):
        ny, nx = shapes[i % len(shapes)]
        daemon.submit(spec.init(rng, (ny, nx)), steps[i % len(steps)],
                      workload=spec.name)


def _verify(daemon: ServingDaemon) -> bool:
    from mpi_and_open_mp_tpu import stencils

    for t in daemon.queue.tickets():
        if t.state != DONE:
            continue
        spec = stencils.get(getattr(t, "workload", "life"))
        ref = stencils.oracle_run(spec, np.asarray(t.board), t.steps)
        if not stencils.parity_ok(spec, t.result, ref):
            return False
    return True


def main(argv=None) -> int:
    from mpi_and_open_mp_tpu.obs import metrics

    args = build_parser().parse_args(argv)
    if args.resume and not (args.checkpoint or args.wal):
        build_parser().error("--resume requires --checkpoint and/or --wal")
    aot_dir = args.aot_cache or os.environ.get("MOMP_AOT_CACHE") or None
    aot = None
    if aot_dir:
        from mpi_and_open_mp_tpu.serve.aotcache import AOTCache

        aot = AOTCache(aot_dir)
        rec_aot_cache = os.path.abspath(aot_dir)
    plans_dir = args.plans or os.environ.get("MOMP_TUNE_PLANS") or None
    plan_store = None
    if plans_dir:
        from mpi_and_open_mp_tpu.tune.plans import PlanStore

        plan_store = PlanStore(plans_dir)
        rec_plans_dir = os.path.abspath(plans_dir)
    try:
        backoff_base, backoff_cap, backoff_jitter = _parse_backoff(
            args.backoff)
    except ValueError as e:
        build_parser().error(str(e))
    policy = ServePolicy(
        max_batch=args.max_batch, max_depth=args.max_depth,
        max_padding_frac=args.max_padding_frac,
        max_wait_s=args.max_wait, request_timeout_s=args.timeout,
        max_retries=args.retries, backoff_base_s=backoff_base,
        backoff_cap_s=backoff_cap, backoff_jitter=backoff_jitter,
        seed=args.seed)
    rec: dict = {"daemon": "serve", "resume": bool(args.resume),
                 "workload": args.workload}
    if aot is not None:
        rec["aot_cache"] = rec_aot_cache
    if plan_store is not None:
        rec["plan_store"] = rec_plans_dir
    try:
        if args.resume:
            daemon, source, detail = ServingDaemon.resume_any(
                wal_path=args.wal, checkpoint_path=args.checkpoint,
                policy=policy, wal_fsync=args.wal_fsync, aot_cache=aot,
                plan_store=plan_store)
            rec["resume_source"] = source
            rec.update(detail)
            rec["resumed_tickets"] = daemon.queue.depth()
        else:
            daemon = ServingDaemon(
                policy, checkpoint_path=args.checkpoint,
                wal_path=args.wal, wal_fsync=args.wal_fsync,
                aot_cache=aot, plan_store=plan_store)
        if aot is not None and args.requests > 0 and args.workload == "life":
            # Preload for the incoming burst too (the resume preload
            # covered only already-pending shapes): every bucket program
            # the burst can need is resident before the first dispatch.
            # Life only — the store holds life bucket executables.
            rec["aot_warm"] = aot.warm(
                [(s, "uint8") for s in _parse_shapes(args.shapes)],
                policy.max_batch)
        _burst(daemon, args)
        t0 = time.perf_counter()
        daemon.serve()
        wall = time.perf_counter() - t0
    except Preempted as e:
        rec.update({"preempted": True, "resume": True,
                    "batches": e.step, "checkpoint": e.checkpoint,
                    **{k: v for k, v in daemon.summary().items()
                       if k != "engines"}})
        print(json.dumps(rec))
        return EXIT_PREEMPTED
    except Exception as e:  # noqa: BLE001 — the line IS the contract
        rec["error"] = f"{type(e).__name__}: {e}"[:300]
        print(json.dumps(rec))
        return 1
    rec.update({"preempted": False, "wall_sec": round(wall, 4),
                **daemon.summary()})
    if daemon._wal is not None:
        daemon._wal.close()
    if rec["resolved"] and wall > 0:
        rec["requests_per_sec"] = round(rec["resolved"] / wall, 2)
    if args.verify:
        rec["verified"] = _verify(daemon)
    if metrics.metrics_on():
        rec["metrics"] = metrics.snapshot()
    print(json.dumps(rec))
    if args.verify and not rec.get("verified"):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
