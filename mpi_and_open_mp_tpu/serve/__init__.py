"""Micro-batching front door over the batched native engines.

The reference serves exactly one workload per process launch (``mpirun
-np N ./life``); the batched execution layer (``ops.bitlife`` B-board
kernels, ``models.LifeSim`` stacked boards) removes the one-board-per-
dispatch limit, and this package supplies the request-collecting layer
on top: callers :meth:`~ShapeBucketBatcher.submit` independent boards,
:meth:`~ShapeBucketBatcher.flush` groups them into shape buckets and
advances each bucket in ONE device dispatch through
``ops.pallas_life.life_run_vmem_batch``.

Why bucketing matters: every distinct ``(B, ny, nx)`` stack shape is
one compiled XLA program, and at ~70 ms host<->device RTT through the
relay an uncontrolled shape set would spend its life retracing. The
batcher therefore (a) keys buckets on board shape+dtype, (b) pads each
dispatch's batch up to a power of two capped at ``max_batch`` (zero
boards, sliced off afterwards — a dead board stays dead under Life's
rule, so padding can never perturb live boards), and (c) leans on the
step count being a RUNTIME scalar on every batched path, so requests
with different step counts share one compiled program. The compiled-
program set is thus at most ``log2(max_batch)+1`` programs per board
shape, verified idle via the ``jit.retrace`` counters
(``obs.metrics.get("jit.retrace", fn="life_batch_...")`` — the PR-4
observability layer ticks them inside each batched jit body, once per
compile).

That small closed program set is also what makes the programs
*persistable*: ``serve.aotcache`` serializes every bucket executable
through ``jax.export`` into a durable on-disk cache, so a restarted
daemon deserializes in milliseconds instead of re-tracing — zero
``jit.retrace`` ticks on a warm resume, with corrupt/stale artifacts
quarantined and parity-gated so a bad cache can only ever cost a fresh
trace, never a wrong answer.
"""

from mpi_and_open_mp_tpu.serve.batcher import (  # noqa: F401
    ShapeBucketBatcher,
    bucket_batch_size,
    retrace_counts,
)
from mpi_and_open_mp_tpu.serve.policy import (  # noqa: F401
    SCALE_ADD,
    SCALE_DRAIN,
    SHED_DEPTH,
    SHED_DISPATCH,
    SHED_PADDING,
    SHED_REASONS,
    SHED_REHOMED,
    SHED_TIMEOUT,
    ElasticController,
    ElasticityPolicy,
    ServePolicy,
    rollup,
)
from mpi_and_open_mp_tpu.serve.queue import (  # noqa: F401
    ServeQueue,
    Ticket,
)
from mpi_and_open_mp_tpu.serve.wal import (  # noqa: F401
    FSYNC_POLICIES,
    TicketWAL,
    WALReplay,
    replay,
)
from mpi_and_open_mp_tpu.serve.aotcache import AOTCache  # noqa: F401
from mpi_and_open_mp_tpu.serve.pool import (  # noqa: F401
    Handle,
    PoolError,
    SessionPool,
)
from mpi_and_open_mp_tpu.serve.daemon import ServingDaemon  # noqa: F401
from mpi_and_open_mp_tpu.serve.router import (  # noqa: F401
    ConsistentHashRing,
    FleetRouter,
)
from mpi_and_open_mp_tpu.serve.fleet import Fleet, WorkerHandle  # noqa: F401
from mpi_and_open_mp_tpu.serve.loadgen import (  # noqa: F401
    SLO,
    LoadgenReport,
    ScenarioMix,
    arrivals_poisson,
    arrivals_trace,
    run_open_loop,
    saturation_knee,
    sweep,
)
