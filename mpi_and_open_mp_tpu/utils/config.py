"""Life board configuration files.

File format (kept byte-compatible with the reference's ``.cfg`` contract,
documented at ``/root/reference/3-life/life_mpi.c:74-78`` and parsed by
``life_init`` at ``3-life/life2d.c:52-72``)::

    <steps>
    <save_steps>
    <nx> <ny>
    <i1> <j1>
    <i2> <j2>
    ...            # live-cell (i, j) pairs until EOF

Coordinates are ``(i, j)`` with ``i`` the x-index (column, 0..nx-1) and ``j``
the y-index (row, 0..ny-1); the board is a periodic torus. Internally the
board is a ``(ny, nx)`` array indexed ``board[j, i]`` (row-major), matching
the reference's linearisation ``ind(i, j) = i + j * nx``
(``3-life/life2d.c:9``).
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np


@dataclasses.dataclass(frozen=True)
class LifeConfig:
    """A parsed Life run configuration."""

    steps: int
    save_steps: int
    nx: int
    ny: int
    cells: np.ndarray  # (n_live, 2) int array of (i, j) pairs

    @property
    def shape(self) -> tuple[int, int]:
        """Board array shape ``(ny, nx)``."""
        return (self.ny, self.nx)

    def board(self) -> np.ndarray:
        """Materialise the initial board as a ``(ny, nx)`` uint8 array."""
        b = np.zeros((self.ny, self.nx), dtype=np.uint8)
        if len(self.cells):
            i = self.cells[:, 0] % self.nx
            j = self.cells[:, 1] % self.ny
            b[j, i] = 1
        return b


def load_config(path: str | os.PathLike) -> LifeConfig:
    """Parse a ``.cfg`` file (native C parser when built, Python otherwise)."""
    from mpi_and_open_mp_tpu.utils import native

    if native.available():
        return native.load_config(path)
    return load_config_py(path)


def load_config_py(path: str | os.PathLike) -> LifeConfig:
    """Pure-Python ``.cfg`` parser (reference semantics: read pairs to EOF)."""
    with open(path) as fd:
        tokens = fd.read().split()
    if len(tokens) < 4:
        raise ValueError(f"{path}: config needs at least steps/save_steps/nx/ny")
    steps, save_steps, nx, ny = (int(t) for t in tokens[:4])
    rest = tokens[4:]
    if len(rest) % 2:
        raise ValueError(f"{path}: dangling cell coordinate")
    cells = np.array([int(t) for t in rest], dtype=np.int64).reshape(-1, 2)
    return LifeConfig(steps=steps, save_steps=save_steps, nx=nx, ny=ny, cells=cells)


def save_config(path: str | os.PathLike, cfg: LifeConfig) -> None:
    """Write a config back out in the reference file format."""
    with open(path, "w") as fd:
        fd.write(f"{cfg.steps}\n{cfg.save_steps}\n{cfg.nx} {cfg.ny}\n")
        for i, j in np.asarray(cfg.cells):
            fd.write(f"{int(i)} {int(j)}\n")


def config_from_board(
    board: np.ndarray, steps: int, save_steps: int
) -> LifeConfig:
    """Build a config whose live-cell list reproduces ``board``."""
    board = np.asarray(board)
    ny, nx = board.shape
    j, i = np.nonzero(board)
    cells = np.stack([i, j], axis=1).astype(np.int64)
    return LifeConfig(steps=steps, save_steps=save_steps, nx=nx, ny=ny, cells=cells)
