"""Wall-clock timing with the reference's measurement contract.

The reference brackets its hot loop with ``MPI_Wtime`` and prints bare
elapsed seconds from one rank (``/root/reference/3-life/life_mpi.c:50,64-67``).
Here the equivalent is ``time.perf_counter`` around fully-materialised device
work: callers must pass results through ``block_until_ready`` (JAX dispatch is
async) before stopping the clock.
"""

from __future__ import annotations

import time


class Timer:
    """Context manager measuring wall seconds; ``.elapsed`` after exit."""

    def __enter__(self) -> "Timer":
        self.start = time.perf_counter()
        self.elapsed = float("nan")
        return self

    def __exit__(self, *exc) -> None:
        self.elapsed = time.perf_counter() - self.start


def append_times_txt(path: str, seconds: float) -> None:
    """Append one wall-clock entry, matching the ``gtime -o times.txt -a``
    accumulation used by the reference launchers (``3-life/run_life.sh:5``)."""
    with open(path, "a") as fd:
        fd.write(f"{seconds:.3f}\n")
