"""Wall-clock timing with the reference's measurement contract.

The reference brackets its hot loop with ``MPI_Wtime`` and prints bare
elapsed seconds from one rank (``/root/reference/3-life/life_mpi.c:50,64-67``).
Here the equivalent is ``time.perf_counter`` around fully-materialised device
work: callers must pass results through ``block_until_ready`` (JAX dispatch is
async) before stopping the clock.
"""

from __future__ import annotations

import time


def anchor_sync(tree, fetch_all: bool = False) -> None:
    """Wait until every array in ``tree`` has actually materialised.

    ``jax.block_until_ready`` has been observed returning early for
    mesh-placed arrays on tunneled-TPU stacks (step-count-independent
    timings are the tell), so after blocking this anchors each mesh-placed
    leaf with a one-element host fetch — from a locally addressable shard,
    so it also works on multi-host arrays — batched into a single
    ``device_get`` (one host RTT, not one per leaf). Single-device leaves
    stay block-only by default: blocking does work for them on the stacks
    observed, and the fetch would add a full host round trip inside timing
    brackets. Pass ``fetch_all=True`` to probe those too, for brackets
    where a guaranteed landing is worth one RTT.
    """
    import jax

    jax.block_until_ready(tree)
    probes = []
    for leaf in jax.tree_util.tree_leaves(tree):
        if getattr(leaf, "sharding", None) is None or not hasattr(
            leaf, "addressable_shards"
        ):
            continue
        if not fetch_all and isinstance(
            leaf.sharding, jax.sharding.SingleDeviceSharding
        ):
            continue
        shard = leaf.addressable_shards[0].data
        if shard.size == 0:
            continue
        probes.append(shard[(slice(0, 1),) * shard.ndim])
    if probes:
        jax.device_get(probes)


class Timer:
    """Context manager measuring wall seconds.

    ``.elapsed`` reads the RUNNING total inside the ``with`` block (a live
    ``perf_counter`` difference — mid-flight progress reads, span
    heartbeats) and freezes at exit. This is the one wall-clock
    implementation in the framework: the span tracer (``obs.trace``) uses
    it as its clock, so spans and bench brackets can never disagree on
    what a second is.
    """

    def __enter__(self) -> "Timer":
        self.start = time.perf_counter()
        self._stopped: float | None = None
        return self

    @property
    def elapsed(self) -> float:
        if self._stopped is None:
            return time.perf_counter() - self.start
        return self._stopped

    def __exit__(self, *exc) -> None:
        self._stopped = time.perf_counter() - self.start


def append_times_txt(path: str, seconds: float) -> None:
    """Append one wall-clock entry, matching the ``gtime -o times.txt -a``
    accumulation used by the reference launchers (``3-life/run_life.sh:5``)."""
    with open(path, "a") as fd:
        fd.write(f"{seconds:.3f}\n")


def write_csv_rows(path: str, rows: list[str]) -> None:
    """(Re)write a CSV artifact whole, creating its directory. The chip
    sweeps call this after EVERY recorded point so a mid-sweep crash
    cannot discard rows bought with scarce chip time."""
    import os

    outdir = os.path.dirname(path)
    if outdir:
        os.makedirs(outdir, exist_ok=True)
    with open(path, "w") as fd:
        fd.write("\n".join(rows) + "\n")
