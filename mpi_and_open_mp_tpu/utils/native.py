"""ctypes bindings to the native C++ runtime IO library (``native/``).

The reference's runtime layer (config parsing + VTK serialisation,
``/root/reference/3-life/life2d.c:52-102``) is compiled C; this framework
keeps that layer native too: ``native/lifeio.cpp`` built as ``liblifeio.so``.
Python falls back transparently when the library hasn't been built
(``make -C native``). Under a NON-editable install the repo-relative
default can't resolve — set ``MOMP_NATIVE_LIB=/path/to/liblifeio.so``
(the fast path is optional either way).
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

_LIB = None
_TRIED = False

# Default resolution assumes the module lives in the repo tree (in-place
# use or an editable install); a NON-editable install has no native/
# sibling, so MOMP_NATIVE_LIB points at the built .so explicitly there.
_HERE = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_FROM_ENV = bool(os.environ.get("MOMP_NATIVE_LIB"))
_SO_PATH = (os.environ.get("MOMP_NATIVE_LIB")
            or os.path.join(_HERE, "native", "liblifeio.so"))


def _load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    if os.environ.get("LIFE_TPU_NO_NATIVE"):
        return None
    try:
        lib = ctypes.CDLL(_SO_PATH)
        lib.lifeio_life_steps_bits  # newest symbol: reject stale builds
    except (OSError, AttributeError) as e:
        # Missing OR out-of-date library (an old .so lacking newer
        # symbols would otherwise AttributeError past this guard) —
        # fall back to the Python implementations; `make -C native`.
        # Quietly for the repo-relative default, but an EXPLICIT
        # MOMP_NATIVE_LIB that fails to load is a misconfiguration the
        # knob exists to fix — surface it instead of silently degrading.
        # (_FROM_ENV, not a live env read: _SO_PATH was frozen at import,
        # so the warning must describe the same snapshot it loaded from.)
        if _FROM_ENV:
            import warnings

            warnings.warn(
                f"MOMP_NATIVE_LIB={_SO_PATH} failed to load"
                f" ({type(e).__name__}: {e}); falling back to the Python"
                " implementations", RuntimeWarning, stacklevel=3)
        return None
    lib.lifeio_load_config.restype = ctypes.c_int
    lib.lifeio_load_config.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_longlong),  # steps, save_steps, nx, ny, ncells
        ctypes.POINTER(ctypes.POINTER(ctypes.c_longlong)),  # cells buffer
    ]
    lib.lifeio_free.restype = None
    lib.lifeio_free.argtypes = [ctypes.POINTER(ctypes.c_longlong)]
    lib.lifeio_write_vtk.restype = ctypes.c_int
    lib.lifeio_write_vtk.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int),
        ctypes.c_longlong,
        ctypes.c_longlong,
    ]
    lib.lifeio_life_steps.restype = None
    lib.lifeio_life_steps.argtypes = [
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_longlong,
        ctypes.c_longlong,
        ctypes.c_longlong,
    ]
    lib.lifeio_life_steps_bits.restype = None
    lib.lifeio_life_steps_bits.argtypes = lib.lifeio_life_steps.argtypes
    _LIB = lib
    return _LIB


def available() -> bool:
    return _load() is not None


def _require():
    lib = _load()
    if lib is None:
        raise RuntimeError(
            f"native lifeio library not available (expected at {_SO_PATH}):"
            " build it with `make -C native` in the repo tree, or point"
            " MOMP_NATIVE_LIB at a built liblifeio.so (required for"
            " non-editable installs, which carry no native/ sibling)"
        )
    return lib


def load_config(path):
    from mpi_and_open_mp_tpu.utils.config import LifeConfig

    lib = _require()
    header = (ctypes.c_longlong * 5)()
    cells_ptr = ctypes.POINTER(ctypes.c_longlong)()
    rc = lib.lifeio_load_config(
        str(path).encode(), header, ctypes.byref(cells_ptr)
    )
    if rc != 0:
        raise ValueError(f"{path}: native config parse failed (rc={rc})")
    steps, save_steps, nx, ny, ncells = (int(v) for v in header)
    try:
        if ncells:
            flat = np.ctypeslib.as_array(cells_ptr, shape=(ncells * 2,)).copy()
            cells = flat.reshape(-1, 2)
        else:
            cells = np.zeros((0, 2), dtype=np.int64)
    finally:
        lib.lifeio_free(cells_ptr)
    return LifeConfig(steps=steps, save_steps=save_steps, nx=nx, ny=ny, cells=cells)


def life_steps(board: np.ndarray, steps: int, bits: bool = False) -> np.ndarray:
    """Advance ``steps`` generations through the native C++ oracle.

    An independent compiled ground truth (same role as the reference's
    ``life2d`` binary) — used by tests to cross-check the NumPy oracle and
    by hosts that want a fast serial path without JAX. ``bits=True``
    selects the bit-packed (64 cells/word) carry-save variant — ~50x
    faster on big boards, itself a third independent implementation.
    """
    lib = _require()
    out = np.ascontiguousarray(board, dtype=np.uint8).copy()
    ny, nx = out.shape
    fn = lib.lifeio_life_steps_bits if bits else lib.lifeio_life_steps
    fn(out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), nx, ny, int(steps))
    return out


def write_vtk(path, board: np.ndarray) -> None:
    lib = _require()
    board = np.ascontiguousarray(board, dtype=np.int32)
    ny, nx = board.shape
    rc = lib.lifeio_write_vtk(
        str(path).encode(),
        board.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        nx,
        ny,
    )
    if rc != 0:
        raise OSError(f"{path}: native VTK write failed (rc={rc})")
