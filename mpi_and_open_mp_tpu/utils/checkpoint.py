"""Orbax checkpointing for simulation state.

The reference's only state serialisation is the ASCII VTK dump
(``/root/reference/3-life/life_mpi.c:120-148``) — a gather-to-root followed
by a per-cell fprintf. This module adds the TPU-native alternative: the
sharded board ``jax.Array`` goes to an Orbax checkpoint directly, so on
multi-host meshes every process writes only its own shards (no
gather-to-root, no host bottleneck), and restore can re-shard onto any
mesh. VTK stays the human-inspectable format; Orbax is the restart format.

Crash safety: ``save`` writes the tree to a ``path + ".tmp"`` sibling and
``os.replace``s it into place, so a kill mid-save (the preemption this
format exists to survive) never leaves a half-written restart dir at
``path`` — readers only ever see the old complete tree or the new one.
The tree carries a CRC32 manifest leaf of the board bytes; ``restore``
verifies it and raises ``ValueError`` with a usable message on any
corrupt/partial/mismatched tree instead of an Orbax traceback.
"""

from __future__ import annotations

import os
import pickle
import shutil
import struct
import zlib

import numpy as np

import jax

_CKPTR = None


def _fsync_dir(path: str | os.PathLike) -> None:
    """fsync the directory CONTAINING ``path`` — the missing half of
    rename-based crash atomicity. ``os.replace`` makes the swap atomic
    against readers, but the rename itself lives in the directory inode:
    until that inode reaches disk, a power cut can roll the directory
    back to the pre-rename entry (or, worse, to neither name on some
    filesystems). Every tmp+fsync+replace sequence in this module ends
    here so the *rename* is as durable as the bytes. Best-effort on
    platforms that cannot open a directory read-only (Windows): the
    atomicity-against-crashed-writers guarantee stands everywhere, the
    power-cut guarantee only where the OS allows it."""
    d = os.path.dirname(os.path.abspath(os.fspath(path))) or "."
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def quarantine(path: str | os.PathLike, label: str = "corrupt") -> str | None:
    """Move a bad artifact aside as ``<path>.<label>.<stamp>`` and return
    the destination (``None`` when the move failed or nothing was there).

    The stamp (UTC time + pid + a collision counter) makes every
    quarantine file unique: a second corrupt resume must never clobber
    the forensic copy of the first — the evidence of TWO independent
    corruptions is itself evidence. The rename is made durable with the
    same parent-directory fsync as every other crash-atomic move here."""
    import time

    path = os.path.abspath(os.fspath(path))
    stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime()) + f".{os.getpid()}"
    dst = f"{path}.{label}.{stamp}"
    n = 0
    while os.path.exists(dst):
        n += 1
        dst = f"{path}.{label}.{stamp}.{n}"
    try:
        os.replace(path, dst)
    except OSError:
        return None
    _fsync_dir(dst)
    return dst


def _checkpointer():
    """Module-cached PyTreeCheckpointer: constructing one spins up thread
    pools and a tensorstore context, too costly to pay per save inside the
    timed simulation loop (the times.txt bracket includes saves)."""
    global _CKPTR
    if _CKPTR is None:
        import orbax.checkpoint as ocp

        _CKPTR = ocp.PyTreeCheckpointer()
    return _CKPTR


def _board_crc(board) -> np.uint32:
    """CRC32 of the uint8 board bytes — the manifest leaf ``restore``
    verifies. 0 (= "unverified") on multi-host boards: no single process
    holds all the bytes, and a per-shard CRC would depend on the mesh."""
    if not getattr(board, "is_fully_addressable", True):
        return np.uint32(0)
    host = np.ascontiguousarray(
        np.asarray(jax.device_get(board), dtype=np.uint8))
    return np.uint32(zlib.crc32(host.tobytes()))


def save(path: str | os.PathLike, board: jax.Array, step: int) -> None:
    """Write ``{board, step, crc}`` as an Orbax checkpoint at ``path``,
    atomically (tmp sibling + rename — module docs)."""
    from mpi_and_open_mp_tpu.obs import metrics, trace
    from mpi_and_open_mp_tpu.utils.timing import Timer

    path = os.path.abspath(os.fspath(path))
    nbytes = int(getattr(board, "nbytes", 0))
    with trace.span("checkpoint.save", step=int(step),
                    bytes=nbytes, path=path), Timer() as t:
        tmp = path + ".tmp"
        # A crashed earlier save may have left a stale sibling; it was
        # never authoritative.
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        _checkpointer().save(
            tmp,
            {"board": board, "step": np.int64(step),
             "crc": _board_crc(board)},
            force=True,
        )
        # os.replace can't overwrite a non-empty dir: clear the old tree
        # first. A kill in the gap loses only the OLD checkpoint (the new
        # one sits complete at tmp); no window ever exposes a partial
        # tree.
        if os.path.exists(path):
            shutil.rmtree(path)
        os.replace(tmp, path)
        _fsync_dir(path)
    metrics.inc("checkpoint.saves")
    metrics.inc("checkpoint.save.bytes", nbytes)
    metrics.observe("checkpoint.save_seconds", t.elapsed)


def restore(path: str | os.PathLike) -> tuple[np.ndarray, int]:
    """Read a checkpoint back to host arrays ``(board, step)``, validated.

    The caller re-shards onto its own mesh (``LifeSim(initial_board=...)``);
    restoring host-side keeps restore mesh-shape-agnostic. Raises
    ``ValueError`` on a missing/corrupt/partial tree or a CRC mismatch.
    """
    from mpi_and_open_mp_tpu.obs import metrics, trace
    from mpi_and_open_mp_tpu.utils.timing import Timer

    path = os.path.abspath(os.fspath(path))
    if not os.path.isdir(path):
        raise ValueError(f"no checkpoint directory at {path}")
    with trace.span("checkpoint.restore", path=path), Timer() as t:
        try:
            tree = _checkpointer().restore(path)
        except Exception as e:
            raise ValueError(
                f"corrupt or partial checkpoint at {path} "
                f"({type(e).__name__}: {e})"[:400]) from e
    metrics.inc("checkpoint.restores")
    metrics.observe("checkpoint.restore_seconds", t.elapsed)
    if not isinstance(tree, dict) or "board" not in tree or "step" not in tree:
        raise ValueError(
            f"checkpoint at {path} is missing its board/step leaves "
            f"(got {sorted(tree) if isinstance(tree, dict) else type(tree)})")
    board = np.asarray(tree["board"])
    if board.ndim != 2:
        raise ValueError(
            f"checkpoint board at {path} has rank {board.ndim}, want 2")
    board = board.astype(np.uint8)
    metrics.inc("checkpoint.restore.bytes", int(board.nbytes))
    step = int(tree["step"])
    if step < 0:
        raise ValueError(f"checkpoint at {path} carries negative step {step}")
    want = int(tree.get("crc", 0))
    if want:  # 0 = legacy/multi-host tree without a verifiable manifest
        got = zlib.crc32(np.ascontiguousarray(board).tobytes())
        if got != want:
            raise ValueError(
                f"checkpoint at {path} failed its CRC manifest "
                f"(stored {want:#010x}, recomputed {got:#010x}) — "
                "the tree is corrupt; fall back to an earlier step")
    return board, step


# --------------------------------------------------------------------------
# Single-file host-state checkpoints (the serving daemon's queue snapshot).
#
# Orbax above serialises DEVICE state (a sharded board) as a directory
# tree; the daemon's pending-request queue is small HOST state (ticket
# order, payload boards, bucket metadata) that must survive a SIGTERM in
# one crash-atomic file. Frame: an ASCII magic line, an 8-byte big-endian
# payload length, a 4-byte CRC32 of the payload, then the pickled payload.
# ``restore_state`` validates frame, length, and CRC BEFORE unpickling, so
# a truncated or garbage file — the tail a killed writer or a corrupt disk
# leaves behind — raises a clean ``ValueError`` naming the failure, never
# a pickle/struct traceback.

STATE_MAGIC = b"MOMP-STATE/1\n"
_STATE_HEADER = struct.Struct(">QI")  # payload length, CRC32


def save_state(path: str | os.PathLike, state) -> None:
    """Write one picklable host-state tree to ``path`` atomically (tmp
    sibling + ``os.replace``, same discipline as :func:`save`)."""
    from mpi_and_open_mp_tpu.obs import metrics, trace

    path = os.path.abspath(os.fspath(path))
    payload = pickle.dumps(state, protocol=pickle.HIGHEST_PROTOCOL)
    blob = (STATE_MAGIC
            + _STATE_HEADER.pack(len(payload), zlib.crc32(payload))
            + payload)
    with trace.span("checkpoint.state_save", path=path, bytes=len(blob)):
        outdir = os.path.dirname(path)
        if outdir:
            os.makedirs(outdir, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as fd:
            fd.write(blob)
            fd.flush()
            os.fsync(fd.fileno())
        os.replace(tmp, path)
        _fsync_dir(path)
    metrics.inc("checkpoint.state_saves")
    metrics.inc("checkpoint.state_save.bytes", len(blob))


def restore_state(path: str | os.PathLike):
    """Read a :func:`save_state` file back, fully validated.

    Raises ``ValueError`` — with the specific failure (missing file, bad
    magic, truncated header/payload, CRC mismatch, undecodable payload) —
    on anything short of a complete verified frame.
    """
    from mpi_and_open_mp_tpu.obs import metrics, trace

    path = os.path.abspath(os.fspath(path))
    with trace.span("checkpoint.state_restore", path=path):
        try:
            with open(path, "rb") as fd:
                blob = fd.read()
        except OSError as e:
            raise ValueError(
                f"no readable state checkpoint at {path} "
                f"({type(e).__name__}: {e})") from e
        head = len(STATE_MAGIC) + _STATE_HEADER.size
        if not blob.startswith(STATE_MAGIC):
            raise ValueError(
                f"state checkpoint at {path} has a bad magic header — "
                "not a MOMP-STATE/1 file (or corrupted at offset 0)")
        if len(blob) < head:
            raise ValueError(
                f"state checkpoint at {path} is truncated inside its "
                f"header ({len(blob)} of {head} header bytes)")
        length, want_crc = _STATE_HEADER.unpack(
            blob[len(STATE_MAGIC):head])
        payload = blob[head:]
        if len(payload) != length:
            raise ValueError(
                f"state checkpoint at {path} is truncated: payload is "
                f"{len(payload)} bytes, header promises {length}")
        got_crc = zlib.crc32(payload)
        if got_crc != want_crc:
            raise ValueError(
                f"state checkpoint at {path} failed its CRC "
                f"(stored {want_crc:#010x}, recomputed {got_crc:#010x}) "
                "— the file is corrupt")
        try:
            state = pickle.loads(payload)
        except Exception as e:  # noqa: BLE001 — any unpickle failure
            raise ValueError(
                f"state checkpoint at {path} passed its CRC but failed "
                f"to decode ({type(e).__name__}: {e})"[:400]) from e
    metrics.inc("checkpoint.state_restores")
    return state
