"""Orbax checkpointing for simulation state.

The reference's only state serialisation is the ASCII VTK dump
(``/root/reference/3-life/life_mpi.c:120-148``) — a gather-to-root followed
by a per-cell fprintf. This module adds the TPU-native alternative: the
sharded board ``jax.Array`` goes to an Orbax checkpoint directly, so on
multi-host meshes every process writes only its own shards (no
gather-to-root, no host bottleneck), and restore can re-shard onto any
mesh. VTK stays the human-inspectable format; Orbax is the restart format.

Crash safety: ``save`` writes the tree to a ``path + ".tmp"`` sibling and
``os.replace``s it into place, so a kill mid-save (the preemption this
format exists to survive) never leaves a half-written restart dir at
``path`` — readers only ever see the old complete tree or the new one.
The tree carries a CRC32 manifest leaf of the board bytes; ``restore``
verifies it and raises ``ValueError`` with a usable message on any
corrupt/partial/mismatched tree instead of an Orbax traceback.
"""

from __future__ import annotations

import os
import shutil
import zlib

import numpy as np

import jax

_CKPTR = None


def _checkpointer():
    """Module-cached PyTreeCheckpointer: constructing one spins up thread
    pools and a tensorstore context, too costly to pay per save inside the
    timed simulation loop (the times.txt bracket includes saves)."""
    global _CKPTR
    if _CKPTR is None:
        import orbax.checkpoint as ocp

        _CKPTR = ocp.PyTreeCheckpointer()
    return _CKPTR


def _board_crc(board) -> np.uint32:
    """CRC32 of the uint8 board bytes — the manifest leaf ``restore``
    verifies. 0 (= "unverified") on multi-host boards: no single process
    holds all the bytes, and a per-shard CRC would depend on the mesh."""
    if not getattr(board, "is_fully_addressable", True):
        return np.uint32(0)
    host = np.ascontiguousarray(
        np.asarray(jax.device_get(board), dtype=np.uint8))
    return np.uint32(zlib.crc32(host.tobytes()))


def save(path: str | os.PathLike, board: jax.Array, step: int) -> None:
    """Write ``{board, step, crc}`` as an Orbax checkpoint at ``path``,
    atomically (tmp sibling + rename — module docs)."""
    from mpi_and_open_mp_tpu.obs import metrics, trace
    from mpi_and_open_mp_tpu.utils.timing import Timer

    path = os.path.abspath(os.fspath(path))
    nbytes = int(getattr(board, "nbytes", 0))
    with trace.span("checkpoint.save", step=int(step),
                    bytes=nbytes, path=path), Timer() as t:
        tmp = path + ".tmp"
        # A crashed earlier save may have left a stale sibling; it was
        # never authoritative.
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        _checkpointer().save(
            tmp,
            {"board": board, "step": np.int64(step),
             "crc": _board_crc(board)},
            force=True,
        )
        # os.replace can't overwrite a non-empty dir: clear the old tree
        # first. A kill in the gap loses only the OLD checkpoint (the new
        # one sits complete at tmp); no window ever exposes a partial
        # tree.
        if os.path.exists(path):
            shutil.rmtree(path)
        os.replace(tmp, path)
    metrics.inc("checkpoint.saves")
    metrics.inc("checkpoint.save.bytes", nbytes)
    metrics.observe("checkpoint.save_seconds", t.elapsed)


def restore(path: str | os.PathLike) -> tuple[np.ndarray, int]:
    """Read a checkpoint back to host arrays ``(board, step)``, validated.

    The caller re-shards onto its own mesh (``LifeSim(initial_board=...)``);
    restoring host-side keeps restore mesh-shape-agnostic. Raises
    ``ValueError`` on a missing/corrupt/partial tree or a CRC mismatch.
    """
    from mpi_and_open_mp_tpu.obs import metrics, trace
    from mpi_and_open_mp_tpu.utils.timing import Timer

    path = os.path.abspath(os.fspath(path))
    if not os.path.isdir(path):
        raise ValueError(f"no checkpoint directory at {path}")
    with trace.span("checkpoint.restore", path=path), Timer() as t:
        try:
            tree = _checkpointer().restore(path)
        except Exception as e:
            raise ValueError(
                f"corrupt or partial checkpoint at {path} "
                f"({type(e).__name__}: {e})"[:400]) from e
    metrics.inc("checkpoint.restores")
    metrics.observe("checkpoint.restore_seconds", t.elapsed)
    if not isinstance(tree, dict) or "board" not in tree or "step" not in tree:
        raise ValueError(
            f"checkpoint at {path} is missing its board/step leaves "
            f"(got {sorted(tree) if isinstance(tree, dict) else type(tree)})")
    board = np.asarray(tree["board"])
    if board.ndim != 2:
        raise ValueError(
            f"checkpoint board at {path} has rank {board.ndim}, want 2")
    board = board.astype(np.uint8)
    metrics.inc("checkpoint.restore.bytes", int(board.nbytes))
    step = int(tree["step"])
    if step < 0:
        raise ValueError(f"checkpoint at {path} carries negative step {step}")
    want = int(tree.get("crc", 0))
    if want:  # 0 = legacy/multi-host tree without a verifiable manifest
        got = zlib.crc32(np.ascontiguousarray(board).tobytes())
        if got != want:
            raise ValueError(
                f"checkpoint at {path} failed its CRC manifest "
                f"(stored {want:#010x}, recomputed {got:#010x}) — "
                "the tree is corrupt; fall back to an earlier step")
    return board, step
