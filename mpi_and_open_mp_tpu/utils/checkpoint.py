"""Orbax checkpointing for simulation state.

The reference's only state serialisation is the ASCII VTK dump
(``/root/reference/3-life/life_mpi.c:120-148``) — a gather-to-root followed
by a per-cell fprintf. This module adds the TPU-native alternative: the
sharded board ``jax.Array`` goes to an Orbax checkpoint directly, so on
multi-host meshes every process writes only its own shards (no
gather-to-root, no host bottleneck), and restore can re-shard onto any
mesh. VTK stays the human-inspectable format; Orbax is the restart format.
"""

from __future__ import annotations

import os

import numpy as np

import jax

_CKPTR = None


def _checkpointer():
    """Module-cached PyTreeCheckpointer: constructing one spins up thread
    pools and a tensorstore context, too costly to pay per save inside the
    timed simulation loop (the times.txt bracket includes saves)."""
    global _CKPTR
    if _CKPTR is None:
        import orbax.checkpoint as ocp

        _CKPTR = ocp.PyTreeCheckpointer()
    return _CKPTR


def save(path: str | os.PathLike, board: jax.Array, step: int) -> None:
    """Write ``{board, step}`` as an Orbax checkpoint at ``path``."""
    path = os.path.abspath(os.fspath(path))
    _checkpointer().save(
        path,
        {"board": board, "step": np.int64(step)},
        force=True,
    )


def restore(path: str | os.PathLike) -> tuple[np.ndarray, int]:
    """Read a checkpoint back to host arrays ``(board, step)``.

    The caller re-shards onto its own mesh (``LifeSim(initial_board=...)``);
    restoring host-side keeps restore mesh-shape-agnostic.
    """
    path = os.path.abspath(os.fspath(path))
    tree = _checkpointer().restore(path)
    return np.asarray(tree["board"], dtype=np.uint8), int(tree["step"])
