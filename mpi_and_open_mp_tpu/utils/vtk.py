"""ASCII VTK 3.0 STRUCTURED_POINTS board snapshots.

Output is format-compatible with the reference's ``life_save_vtk``
(``/root/reference/3-life/life_mpi.c:120-148``): header with
``DIMENSIONS nx+1 ny+1 1``, ``CELL_DATA nx*ny``, scalar field ``life``,
one cell value per line in ``ind = i + j*nx`` order (row-major over a
``(ny, nx)`` array). Snapshots land in a ``vtk/`` directory created on
demand, files named ``life_%06d.vtk`` by step index.
"""

from __future__ import annotations

import os
import re

import numpy as np


def vtk_path(outdir: str | os.PathLike, step: int) -> str:
    return os.path.join(outdir, f"life_{step:06d}.vtk")


def write_vtk(path: str | os.PathLike, board: np.ndarray) -> None:
    """Write one board snapshot (native C writer when built, Python otherwise)."""
    from mpi_and_open_mp_tpu.utils import native

    board = np.asarray(board, dtype=np.int32)
    if native.available():
        native.write_vtk(path, board)
        return
    write_vtk_py(path, board)


def write_vtk_py(path: str | os.PathLike, board: np.ndarray) -> None:
    board = np.asarray(board, dtype=np.int32)
    ny, nx = board.shape
    lines = [
        "# vtk DataFile Version 3.0",
        "Created by mpi_and_open_mp_tpu",
        "ASCII",
        "DATASET STRUCTURED_POINTS",
        f"DIMENSIONS {nx + 1} {ny + 1} 1",
        "SPACING 1 1 0.0",
        "ORIGIN 0 0 0.0",
        f"CELL_DATA {nx * ny}",
        "SCALARS life int 1",
        "LOOKUP_TABLE life_table",
    ]
    body = "\n".join(str(v) for v in board.ravel())
    with open(path, "w") as fd:
        fd.write("\n".join(lines) + "\n" + body + "\n")


_DIMS_RE = re.compile(r"DIMENSIONS\s+(\d+)\s+(\d+)\s+(\d+)")


def read_vtk(path: str | os.PathLike) -> np.ndarray:
    """Read a snapshot back into a ``(ny, nx)`` uint8 array (for tests)."""
    with open(path) as fd:
        text = fd.read()
    m = _DIMS_RE.search(text)
    if not m:
        raise ValueError(f"{path}: no DIMENSIONS header")
    nx, ny = int(m.group(1)) - 1, int(m.group(2)) - 1
    # Cell values start after the LOOKUP_TABLE line.
    body = text.split("LOOKUP_TABLE", 1)[1].split("\n", 1)[1]
    vals = np.array(body.split(), dtype=np.int64)
    if vals.size != nx * ny:
        raise ValueError(f"{path}: expected {nx * ny} cells, got {vals.size}")
    return vals.reshape(ny, nx).astype(np.uint8)
