from mpi_and_open_mp_tpu.utils.config import LifeConfig, load_config, save_config  # noqa: F401
from mpi_and_open_mp_tpu.utils.vtk import write_vtk, read_vtk  # noqa: F401
from mpi_and_open_mp_tpu.utils.timing import Timer  # noqa: F401
