"""Validation guards and the engine-fallback retry policy.

The generalisation of the engine-keyed retry that ``gated_parity_check``
(``parallel/context.py``) grew for the Pallas flash kernel: *run a ranked
list of engines, validate each result, fall through on failure, stamp the
provenance of whichever engine survived*. Recovery provenance carries the
``:recovered`` suffix and lands in a process-wide log so recorders
(``bench.py``) can publish it — a silently self-healed run is a lie in a
measurement artifact.

Guards sit OUTSIDE the jit boundary on purpose: a validator is a host
fetch (``all_finite`` pulls the output back), which would serialise the
async dispatch pipeline if it ran per step on the hot path. They are armed
only when a chaos plan is active (``MOMP_CHAOS``) or explicitly via
``MOMP_GUARD=1`` — the default hot path pays a single ``is None`` check.
"""

from __future__ import annotations

import collections
import os

from mpi_and_open_mp_tpu.robust import chaos


class FallbackExhausted(RuntimeError):
    """Every engine in a :func:`with_fallback` chain failed validation."""

    def __init__(self, notes: list[str]):
        self.notes = list(notes)
        super().__init__(
            "all engines failed: " + ("; ".join(notes) or "(no notes)")
        )


def with_fallback(engines, validator=None, *, retries: int = 1):
    """Run ``(name, thunk)`` engines in order until one validates.

    ``validator(result) -> bool`` decides acceptance (``None`` accepts the
    first result that doesn't raise); each engine gets up to ``retries``
    attempts. Returns ``(result, stamp, notes)`` where ``stamp`` is the
    engine name — suffixed ``:recovered`` whenever anything failed before
    it, so provenance distinguishes a first-try pass from a self-healed
    one. Raises :class:`FallbackExhausted` when the chain runs dry.
    """
    from mpi_and_open_mp_tpu.obs import metrics

    notes: list[str] = []
    clean = True
    for name, thunk in engines:
        for _ in range(max(1, retries)):
            try:
                result = thunk()
            except Exception as e:
                notes.append(f"{name}: {type(e).__name__}: {e}"[:160])
                clean = False
                continue
            if validator is not None:
                metrics.inc("guard.validation", engine=name)
                try:
                    ok = bool(validator(result))
                except Exception as e:
                    notes.append(
                        f"{name} validator: {type(e).__name__}: {e}"[:160])
                    ok = False
                if not ok:
                    metrics.inc("guard.validation_failed", engine=name)
                    if not notes or not notes[-1].startswith(f"{name} "):
                        notes.append(f"{name} failed validation")
                    clean = False
                    continue
            return result, (name if clean else f"{name}:recovered"), notes
    raise FallbackExhausted(notes)


def all_finite(x) -> bool:
    """NaN/Inf divergence validator — a full host fetch; guard-path only."""
    import numpy as np
    import jax

    return bool(np.isfinite(np.asarray(jax.device_get(x))).all())


def guard_env() -> bool:
    """``MOMP_GUARD=1`` arms the guards without any chaos plan."""
    return os.environ.get("MOMP_GUARD", "0") == "1"


def guards_active() -> bool:
    """Whether validators should run: an (unsuppressed) chaos plan that
    didn't opt out via ``noguard``, or the explicit ``MOMP_GUARD=1``."""
    plan = chaos.active_plan()
    return (plan is not None and plan.guard) or guard_env()


# Recovery provenance lives in two places with distinct jobs: aggregate
# COUNTS go to the metrics registry (``recovery{stamp=...}`` counters —
# what bench's ``metrics`` sub-object and trace_report's summary read),
# and the ORDERED recent stamps sit in this bounded ring buffer (what
# bench's ``recovered`` list publishes). The cap keeps a pathological
# re-fire loop from growing process memory without bound; 256 stamps is
# far beyond anything a sane run produces, so the artifact view is
# lossless in practice while the registry's counts stay exact always.
RECOVERY_LOG_CAP = 256
_RECOVERIES: collections.deque[str] = collections.deque(
    maxlen=RECOVERY_LOG_CAP)


def record_recovery(stamp: str) -> None:
    """The one funnel every recovery passes through: ring buffer +
    metrics counter + trace event (``bench.py`` publishes the first two;
    a ``MOMP_TRACE`` sink sees each recovery in stream order)."""
    from mpi_and_open_mp_tpu.obs import metrics, trace

    _RECOVERIES.append(stamp)
    metrics.inc("recovery", stamp=stamp)
    trace.event("recovery", stamp=stamp)


def recovery_log() -> list[str]:
    """The most recent recovery stamps, oldest first (capped at
    :data:`RECOVERY_LOG_CAP`)."""
    return list(_RECOVERIES)


def reset_recovery_log() -> None:
    """Empty the ring buffer (tests; registry counters are untouched —
    use ``obs.metrics.reset()`` for those)."""
    _RECOVERIES.clear()


# Pre-obs name, kept working: existing tests and harness code call it.
clear_recovery_log = reset_recovery_log
