"""Preemption-safe shutdown: signal-triggered checkpoint flush.

The reference's answer to a preempted PBS job was to requeue and restart
from step 0. Here a SIGTERM/SIGINT lands as a *flag* checked at segment
boundaries of ``LifeSim.run``: the loop flushes a final checkpoint and
raises :class:`Preempted`, which drivers translate to exit code 75
(EX_TEMPFAIL) — the ``tpu_queue_loop.sh`` queue keeps the job and its
``--resume`` continues the bracket from the flushed step.

Handlers only *set the flag* — no checkpoint IO, no device work, nothing
async-signal-unsafe runs inside the handler itself. The flush happens in
the run loop, between dispatches, where the board is a complete step.
"""

from __future__ import annotations

import contextlib
import signal
import threading

EXIT_PREEMPTED = 75  # EX_TEMPFAIL: transient, resumable — requeue me


class Preempted(RuntimeError):
    """A run stopped early with its state flushed; resume to continue."""

    def __init__(self, step: int, checkpoint: str | None = None,
                 signum: int | None = None):
        self.step = int(step)
        self.checkpoint = checkpoint
        self.signum = signum
        how = (f"signal {signum}" if signum is not None else "chaos plan")
        where = f"; checkpoint {checkpoint}" if checkpoint else ""
        super().__init__(f"preempted at step {step} by {how}{where}")


class SimulatedPreemption(Preempted):
    """The ``MOMP_CHAOS`` ``preempt=<k>`` fault — same recovery contract
    as a real signal, minus the dying process."""


class SignalWatch:
    """The flag a run loop polls; ``fired`` is the signum or ``None``."""

    def __init__(self):
        self.fired: int | None = None


@contextlib.contextmanager
def flush_on_signal(enabled: bool = True):
    """Arm SIGTERM/SIGINT to request a checkpoint flush at the next
    segment boundary. Yields a :class:`SignalWatch`; previous handlers
    are restored on exit. A no-op (always-unfired watch) when disabled
    or off the main thread (signal.signal would raise there)."""
    watch = SignalWatch()
    if not enabled or threading.current_thread() is not threading.main_thread():
        yield watch
        return
    prev = {}

    def handler(signum, frame):
        watch.fired = signum

    try:
        for s in (signal.SIGTERM, signal.SIGINT):
            try:
                prev[s] = signal.signal(s, handler)
            except (ValueError, OSError):  # exotic embedding; stay a no-op
                pass
        yield watch
    finally:
        for s, h in prev.items():
            signal.signal(s, h)
