"""Fault injection, guards, watchdogged dispatch, preemption-safe resume.

The robustness layer of the stack — four small modules threaded through
``parallel/``, ``models/``, ``bench.py`` and the launchers:

``chaos``
    Env-driven (``MOMP_CHAOS``) deterministic fault injection: NaN/Inf
    ring-hop poisoning, corrupted/dropped halo rows, dispatch delay,
    simulated preemption. Zero injection code reachable when unset.
``guards``
    ``with_fallback(engines, validator)`` — the general engine-ranked
    retry with ``:recovered`` provenance — plus the validators and the
    process-wide recovery log recorders publish.
``watchdog``
    Subprocess device probe with bounded exponential backoff and
    CPU-degrade on exhaustion; probes abandon, never kill (the relay
    rule).
``preempt``
    SIGTERM/SIGINT → checkpoint-flush-at-segment-boundary → exit 75,
    and the :class:`Preempted` contract drivers/queues key on.
"""

from mpi_and_open_mp_tpu.robust import chaos, guards, preempt, watchdog  # noqa: F401
from mpi_and_open_mp_tpu.robust.chaos import FaultPlan, active_plan  # noqa: F401
from mpi_and_open_mp_tpu.robust.guards import (  # noqa: F401
    FallbackExhausted,
    with_fallback,
)
from mpi_and_open_mp_tpu.robust.preempt import (  # noqa: F401
    EXIT_PREEMPTED,
    Preempted,
    SimulatedPreemption,
)
