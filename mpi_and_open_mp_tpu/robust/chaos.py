"""Deterministic fault injection — the chaos layer of the robust subsystem.

The fabric this framework rides (tunneled single-tenant TPU, preemptible
hosts, a relay that wedges when clients die mid-claim) fails in ways the
reference's PBS workflow only ever answered with "rerun the job". This
module makes those failures *injectable* so every recovery path in the
stack (``robust.guards``, ``LifeSim`` consistency probes, checkpoint
resume) is testable on the 8-virtual-device CPU mesh, deterministically,
without hardware.

Faults are driven entirely by the ``MOMP_CHAOS`` environment variable — a
semicolon-separated spec::

    MOMP_CHAOS="nan_hop=1;halo=corrupt;delay=0.01;preempt=60;seed=7"

Tokens:

``nan_hop=<j>`` / ``inf_hop=<j>``
    Poison the K/V partials of ring-attention hop ``j`` with NaN / +inf
    (``parallel/context.py`` fold engines, jnp and per-hop Pallas alike).
``halo=corrupt`` / ``halo=drop``
    Corrupt the ghost rows of every traced halo exchange with seeded
    out-of-range values, or zero them (the exchange "never arrived") —
    ``parallel/halo.py``.
``delay=<seconds>``
    Host-side artificial dispatch delay per guarded run segment and per
    fabric ping (``parallel/fabric.py``) — simulates a congested fabric
    or a slow relay without touching traced code.
``preempt=<step>``
    Raise :class:`~mpi_and_open_mp_tpu.robust.preempt.SimulatedPreemption`
    when a ``LifeSim.run`` crosses global step ``<step>`` (after flushing
    a checkpoint when one is configured) — the SIGTERM rehearsal. The
    serving daemon (``serve.daemon``) reads the same token at BATCH
    granularity: its supervised loop preempts after dispatching
    ``<step>`` batches, checkpoint flushed, same exit-75 contract.
``serve_fail=<k>``
    Fail the first ``<k>`` serve-daemon batch dispatches at their
    primary engine (:func:`take_serve_fault` consumes the budget) — the
    mid-queue fault that drives the daemon's retry/degrade ladder in the
    chaos soak.
``crash=<site>:<k>``
    Hard-kill the process (``os._exit(137)`` — indistinguishable from a
    SIGKILL to everything outside it: no atexit, no finally, no signal
    handler) on the ``<k>``-th arrival at the named instrumented site.
    Sites: ``post-admit`` (ticket admitted to the in-memory queue,
    journal record NOT yet written), ``mid-frame`` (half of a WAL frame
    written to the OS, then death — the torn-tail rehearsal),
    ``post-dispatch`` (batch computed, RESOLVE record NOT yet
    journaled). The write-ahead journal's crash-matrix test drives all
    three to prove the per-fsync-policy loss bounds in
    ``serve/wal.py``. The session-pool lifecycle adds four more, each
    firing AFTER its handle-lifecycle frame is journaled but BEFORE the
    pool action runs: ``post-create``, ``post-step``, ``post-snapshot``,
    ``post-evict`` — the pool crash matrix proves resume re-materializes
    exactly the journaled state (a journaled-but-unapplied step is
    applied on resume; nothing acked is ever lost). The fleet membership
    protocol adds two more: ``post-rejoin`` (a rejoining/destination
    worker journaled a claimed session's CREATE+STEP handshake frames,
    the source's EVICT frame NOT yet written — a kill here leaves the
    session journaled at BOTH workers with identical resumable state,
    the at-most-duplicated, never-lost edge) and ``mid-drain`` (a
    drained worker's bucket was adopted — journaled — at its
    destination, the source's ``re-homed`` SHED frame NOT yet written —
    same duplication-not-loss edge for tickets). The membership crash
    matrix drives both across a kill -9 and asserts the books still
    balance over exactly the acked set.
``kill_worker=<i>:<k>``
    Fleet drill: hard-kill (``os._exit(137)``) the serving worker whose
    ``worker_index`` is ``<i>`` on its ``<k>``-th batch dispatch, after
    the DISPATCH frame hits the journal but before any engine runs — a
    mid-dispatch death, so the router's WAL replay must see the chunk
    in-flight and re-home it. Every process of a fleet shares one
    ``MOMP_CHAOS`` value; the index match makes exactly one worker the
    victim (:func:`kill_worker_armed` counts per-process arrivals, and
    processes with a different — or no — worker index never count).
``aot_corrupt=<kind>:<k>``
    Damage the first ``<k>`` AOT-cache artifacts ON DISK immediately
    after their crash-atomic save (:func:`take_aot_corrupt` consumes the
    budget). Kinds: ``bitflip`` (one payload byte flipped — the CRC
    catches it on the next load, the ``aot:corrupt`` quarantine path) and
    ``skew`` (envelope rewritten with a fake jax version in the stored
    fingerprint — valid CRC, exercises the key-stale rejection). The
    in-memory program the saving process holds stays good, so the fault
    lands where real bit rot does: in the NEXT process's warm resume.
``seed=<int>``
    Seed for corrupted-value generation (default 0).
``noguard``
    Inject without arming the guards — the test aid that proves a fault
    actually lands (the run must then *diverge*).

Injection decisions are made at TRACE time: a poisoned trace stays
poisoned for every execution of that compiled program ("sticky" faults —
a corrupted exchange corrupts every step through it), and recovery paths
re-trace under :func:`suppressed` to get a clean program. When
``MOMP_CHAOS`` is unset, :func:`active_plan` returns ``None`` and every
hook degenerates to a single ``is None`` check — no injection ops are
ever built into a program, no jit-cache key changes, nothing reachable.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os

_HOP_KINDS = ("nan", "inf")
_HALO_KINDS = ("corrupt", "drop")

#: Instrumented hard-kill sites for the ``crash=<site>:<k>`` token.
CRASH_SITES = ("post-admit", "mid-frame", "post-dispatch",
               "post-create", "post-step", "post-snapshot", "post-evict",
               "post-rejoin", "mid-drain")

#: The exit status a hard kill reports — 128+SIGKILL, so a requeue loop
#: or CI harness cannot tell an injected crash from a real ``kill -9``.
CRASH_EXIT = 137

#: Artifact-damage modes for the ``aot_corrupt=<kind>:<k>`` token.
AOT_CORRUPT_KINDS = ("bitflip", "skew")


@dataclasses.dataclass
class FaultPlan:
    """A parsed ``MOMP_CHAOS`` spec plus its (tiny) runtime state."""

    raw: str
    seed: int = 0
    hop_poison: tuple[str, int] | None = None  # ("nan"|"inf", hop index)
    halo_fault: str | None = None  # "corrupt" | "drop"
    delay_s: float = 0.0
    preempt_step: int | None = None
    guard: bool = True
    preempt_fired: bool = False  # in-process refire latch
    serve_fail: int = 0  # total serve-dispatch faults to inject
    serve_failed: int = 0  # runtime count consumed so far
    crash_site: str | None = None  # instrumented site to hard-kill at
    crash_at: int = 0  # 1-based arrival count that fires the kill
    crash_hits: int = 0  # runtime arrivals counted so far
    kill_worker_idx: int | None = None  # fleet worker index to hard-kill
    kill_worker_at: int = 0  # 1-based dispatch count that fires the kill
    kill_worker_hits: int = 0  # runtime dispatches counted so far
    aot_corrupt_kind: str | None = None  # "bitflip" | "skew"
    aot_corrupt: int = 0  # total artifact saves to damage
    aot_corrupted: int = 0  # runtime count consumed so far

    @classmethod
    def parse(cls, raw: str) -> "FaultPlan":
        plan = cls(raw=raw)
        for token in raw.split(";"):
            token = token.strip()
            if not token:
                continue
            key, _, val = token.partition("=")
            try:
                if key in ("nan_hop", "inf_hop"):
                    plan.hop_poison = (key[:3], int(val))
                elif key == "halo":
                    if val not in _HALO_KINDS:
                        raise ValueError(f"want one of {_HALO_KINDS}")
                    plan.halo_fault = val
                elif key == "delay":
                    plan.delay_s = float(val)
                    if plan.delay_s < 0:
                        raise ValueError("negative delay")
                elif key == "preempt":
                    plan.preempt_step = int(val)
                elif key == "serve_fail":
                    plan.serve_fail = int(val)
                    if plan.serve_fail < 0:
                        raise ValueError("negative serve_fail")
                elif key == "crash":
                    site, _, k = val.partition(":")
                    if site not in CRASH_SITES:
                        raise ValueError(f"want one of {CRASH_SITES}")
                    plan.crash_site = site
                    plan.crash_at = int(k) if k else 1
                    if plan.crash_at < 1:
                        raise ValueError("crash count must be >= 1")
                elif key == "kill_worker":
                    idx, _, k = val.partition(":")
                    plan.kill_worker_idx = int(idx)
                    if plan.kill_worker_idx < 0:
                        raise ValueError("worker index must be >= 0")
                    plan.kill_worker_at = int(k) if k else 1
                    if plan.kill_worker_at < 1:
                        raise ValueError("kill count must be >= 1")
                elif key == "aot_corrupt":
                    kind, _, k = val.partition(":")
                    if kind not in AOT_CORRUPT_KINDS:
                        raise ValueError(f"want one of {AOT_CORRUPT_KINDS}")
                    plan.aot_corrupt_kind = kind
                    plan.aot_corrupt = int(k) if k else 1
                    if plan.aot_corrupt < 1:
                        raise ValueError("aot_corrupt count must be >= 1")
                elif key == "seed":
                    plan.seed = int(val)
                elif key == "noguard" and not val:
                    plan.guard = False
                else:
                    raise ValueError("unknown token")
            except ValueError as e:
                raise ValueError(
                    f"MOMP_CHAOS: bad token {token!r} in {raw!r} ({e})"
                ) from None
        return plan

    def preempt_pending(self, step: int) -> bool:
        """Will the preemption still fire for a run currently at ``step``?

        False once fired in this process, and false when the run already
        starts at/after the preempt step — a ``--resume`` of the same
        spec must continue, not re-die at the step it resumed from.
        """
        return (
            self.preempt_step is not None
            and not self.preempt_fired
            and step < self.preempt_step
        )


_CACHE: tuple[str | None, FaultPlan | None] = (None, None)
_SUPPRESS = 0


def active_plan() -> FaultPlan | None:
    """The live :class:`FaultPlan`, or ``None`` when ``MOMP_CHAOS`` is
    unset/empty or injection is :func:`suppressed`. Cached per spec value
    so runtime state (the preemption latch) persists across calls."""
    global _CACHE
    if _SUPPRESS:
        return None
    raw = os.environ.get("MOMP_CHAOS", "")
    if not raw:
        return None
    if _CACHE[0] != raw:
        _CACHE = (raw, FaultPlan.parse(raw))
    return _CACHE[1]


def reset() -> None:
    """Drop the cached plan (tests switch specs mid-process)."""
    global _CACHE
    _CACHE = (None, None)


@contextlib.contextmanager
def suppressed():
    """No injection inside: recovery paths re-trace their programs here so
    a transient fault does not re-fire on the very dispatch that retries
    it (:func:`active_plan` returns ``None`` within)."""
    global _SUPPRESS
    _SUPPRESS += 1
    try:
        yield
    finally:
        _SUPPRESS -= 1


def trace_key(tag: str):
    """Jit-cache salt for chaos-aware dispatches: a poisoned trace must
    never be cache-shared with a clean one. ``None`` (the no-chaos key)
    whenever no plan is active."""
    plan = active_plan()
    return None if plan is None else (tag, plan.raw)


def hop_poison_spec() -> tuple[str, int] | None:
    """Trace-time query for the ring fold engines: ``(kind, hop)`` to
    poison, or ``None`` (no plan / suppressed / no hop fault)."""
    plan = active_plan()
    return None if plan is None else plan.hop_poison


def poison_hop(kb, vb, j, spec):
    """Poison a ring hop's K/V partials when ``j`` equals the planned hop.

    ``j`` may be a python int (the final unrolled fold) or a traced loop
    index: the hit test rides the program as data, so one traced fold
    body poisons exactly the planned hop at runtime.
    """
    import jax.numpy as jnp

    kind, hop = spec
    bad = jnp.float32(jnp.nan if kind == "nan" else jnp.inf)
    m = jnp.where(jnp.asarray(j) == hop, bad, jnp.float32(0))
    return kb + m.astype(kb.dtype), vb + m.astype(vb.dtype)


def poisoned_fold(fold, spec):
    """Wrap a ring fold ``(j, state, kb, vb) -> state`` so the planned
    hop's K/V arrive poisoned."""

    def wrapped(j, state, kb, vb):
        kb, vb = poison_hop(kb, vb, j, spec)
        return fold(j, state, kb, vb)

    return wrapped


def halo_ghost_spec() -> tuple[str, int] | None:
    """Trace-time query for the halo exchange: ``(kind, seed)`` to apply
    to ghost rows/columns, or ``None``."""
    plan = active_plan()
    if plan is None or plan.halo_fault is None:
        return None
    return (plan.halo_fault, plan.seed)


def corrupt_ghost(ghost, spec):
    """A faulted ghost block: zeroed ("drop" — the exchange never
    arrived) or filled with a seeded out-of-range value ("corrupt")."""
    import numpy as np
    import jax.numpy as jnp

    kind, seed = spec
    if kind == "drop":
        return jnp.zeros_like(ghost)
    val = int(np.random.default_rng(seed).integers(2, 200))
    return jnp.full_like(ghost, val)


def take_serve_fault() -> bool:
    """Consume one serve-dispatch fault from the plan's ``serve_fail``
    budget: ``True`` means "this dispatch must fail" (the daemon's
    primary-engine thunk raises). Stateful like the preemption latch —
    each call that returns ``True`` spends one fault, so the first ``k``
    dispatches fail and every later one runs clean. ``False`` whenever no
    plan is active or injection is :func:`suppressed` (recovery
    re-dispatches run clean by construction)."""
    plan = active_plan()
    if plan is None or plan.serve_failed >= plan.serve_fail:
        return False
    plan.serve_failed += 1
    return True


def take_aot_corrupt() -> str | None:
    """Consume one artifact-damage fault from the plan's ``aot_corrupt``
    budget: the kind (``"bitflip"``/``"skew"``) to apply to the artifact
    just saved, or ``None``. Stateful like :func:`take_serve_fault` —
    the first ``k`` saves are damaged, every later one stays clean — and
    inert when no plan is active or injection is :func:`suppressed`."""
    plan = active_plan()
    if plan is None or plan.aot_corrupted >= plan.aot_corrupt:
        return None
    plan.aot_corrupted += 1
    return plan.aot_corrupt_kind


def crash_armed(site: str) -> bool:
    """Count one arrival at instrumented ``site``; ``True`` exactly when
    this arrival is the planned ``<k>``-th — the caller must then tear
    whatever the site tears (a partial frame write, nothing) and call
    :func:`crash_now`. Counting is per-site-name against the single
    planned site, stateful like the preemption latch, and inert (no
    counting) when no plan targets this site or injection is
    :func:`suppressed`."""
    plan = active_plan()
    if plan is None or plan.crash_site != site:
        return False
    plan.crash_hits += 1
    return plan.crash_hits == plan.crash_at


def kill_worker_armed(worker_index: int | None) -> bool:
    """Count one batch dispatch of fleet worker ``worker_index``;
    ``True`` exactly when this dispatch is the planned ``<k>``-th of the
    planned victim — the caller must then :func:`crash_now`. Inert (no
    counting) for processes with no worker index, a non-matching index,
    no plan, or :func:`suppressed` injection — the whole fleet shares
    one ``MOMP_CHAOS`` value and only the victim ever dies."""
    plan = active_plan()
    if (plan is None or worker_index is None
            or plan.kill_worker_idx != worker_index):
        return False
    plan.kill_worker_hits += 1
    return plan.kill_worker_hits == plan.kill_worker_at


def crash_now() -> None:
    """Die as hard as ``kill -9``: ``os._exit`` runs no atexit hooks, no
    ``finally`` blocks, no signal handlers, flushes nothing — the point
    is that ONLY what was already durably journaled survives."""
    os._exit(CRASH_EXIT)


def dispatch_delay() -> float:
    """Seconds of host-side delay to inject per guarded dispatch (0.0
    when inactive)."""
    plan = active_plan()
    return 0.0 if plan is None else plan.delay_s
