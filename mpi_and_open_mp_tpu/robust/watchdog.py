"""Watchdogged device dispatch: probe, bounded backoff, CPU-degrade.

The generalisation of ``bench.py``'s backend probe. The failure mode it
exists for: a wedged axon relay (observed after a TPU client was killed
mid-claim) makes ``jax.devices()`` hang indefinitely *in this process
too* — so device discovery is probed in a subprocess first, and a caller
whose probes run dry degrades to CPU (honestly labelled via a ``degraded``
field in its artifact) instead of hanging the harness.

Two hard rules, inherited from the relay's operational history
(.claude/skills/verify/SKILL.md):

* A hung probe child is ABANDONED, never killed — a killed mid-claim
  client wedges the relay for hours, right before the measurement the
  probe exists to protect. The orphan completes harmlessly or fails out
  on the relay's own clock.
* Backoff is BOUNDED and deterministic (exponential, capped): an
  unbounded retry loop against a wedged relay is just a slower hang.
"""

from __future__ import annotations

import dataclasses
import random
import subprocess
import sys
import tempfile
import time
from typing import Iterator


def probe_once(timeout_s: float) -> tuple[bool, str]:
    """Can a subprocess finish jax device discovery in time?

    On timeout the child is abandoned un-killed (module docs); its stderr
    tail rides the failure note — the relay error in it is what an
    operator needs to diagnose.
    """
    with tempfile.TemporaryFile() as err:
        child = subprocess.Popen(
            [sys.executable, "-c", "import jax; jax.devices()"],
            stdout=subprocess.DEVNULL, stderr=err,
        )

        def tail() -> str:
            err.seek(0)
            text = err.read().decode(errors="replace").strip()
            return f": ...{text[-160:]}" if text else ""

        try:
            rc = child.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            return False, ("TimeoutExpired: discovery hung; probe "
                           "abandoned un-killed" + tail())
        if rc == 0:
            return True, ""
        return False, f"probe exit {rc}" + tail()


def backoff(base_s: float = 2.0, cap_s: float = 60.0, *,
            jitter: float = 0.0, seed: int | None = None,
            ) -> Iterator[float]:
    """Capped-exponential waits as a PURE generator: base, 2·base, 4·base,
    ... ≤ cap, each wait scaled by a seeded jitter factor drawn uniformly
    from ``[1 - jitter, 1]``.

    The jitter is the thundering-herd guard: when ``tpu_queue_loop.sh``
    requeues several preempted jobs at once, identical schedules would
    march every retry back onto the single-tenant relay in lockstep —
    seeded desynchronisation spreads them while staying reproducible
    (same seed, same schedule; the tests assert the sequence without
    sleeping). ``jitter=0`` (the default) is the exact legacy schedule.
    The generator never sleeps and never ends — consumers take as many
    waits as their attempt budget allows.
    """
    if not 0.0 <= jitter <= 1.0:
        raise ValueError(f"jitter must be in [0, 1], got {jitter}")
    rng = random.Random(seed)
    i = 0
    while True:
        wait = min(cap_s, base_s * (2 ** i))
        if jitter:
            wait *= 1.0 - jitter * rng.random()
        yield wait
        # Past the cap the exponent no longer matters; freezing it keeps
        # the generator truly unbounded (no overflow at absurd i).
        if base_s * (2 ** i) < cap_s:
            i += 1


def backoff_schedule(n: int, base_s: float = 2.0, cap_s: float = 60.0,
                     *, jitter: float = 0.0,
                     seed: int | None = None) -> list[float]:
    """The first ``n`` waits of :func:`backoff` as a list (legacy shape;
    ``jitter=0`` keeps the original deterministic schedule)."""
    gen = backoff(base_s, cap_s, jitter=jitter, seed=seed)
    return [next(gen) for _ in range(max(0, n))]


@dataclasses.dataclass
class ProbeResult:
    ok: bool
    why: str  # last failure note ("" on success)
    attempts: int
    waited_s: float  # total backoff slept

    @property
    def degraded(self) -> bool:
        """The one boolean recorders put in their JSON line."""
        return not self.ok


def probe_devices(timeout_s: float, attempts: int = 1,
                  backoff_s: float = 2.0, cap_s: float = 60.0,
                  probe=probe_once, sleep=time.sleep, *,
                  jitter: float = 0.0,
                  seed: int | None = None) -> ProbeResult:
    """Probe device discovery up to ``attempts`` times with bounded
    exponential backoff between failures (optionally seeded-jittered —
    see :func:`backoff`). ``probe``/``sleep`` are injectable for tests.
    Never raises: exhaustion is a normal outcome the caller answers with
    CPU degradation, not an exception."""
    attempts = max(1, int(attempts))
    waits = backoff_schedule(attempts - 1, backoff_s, cap_s,
                             jitter=jitter, seed=seed)
    why = ""
    waited = 0.0
    for a in range(attempts):
        ok, why = probe(timeout_s)
        if ok:
            return ProbeResult(True, "", a + 1, waited)
        if a < len(waits):
            sleep(waits[a])
            waited += waits[a]
    return ProbeResult(False, why, attempts, waited)
