"""Watchdogged device dispatch: probe, bounded backoff, CPU-degrade.

The generalisation of ``bench.py``'s backend probe. The failure mode it
exists for: a wedged axon relay (observed after a TPU client was killed
mid-claim) makes ``jax.devices()`` hang indefinitely *in this process
too* — so device discovery is probed in a subprocess first, and a caller
whose probes run dry degrades to CPU (honestly labelled via a ``degraded``
field in its artifact) instead of hanging the harness.

Two hard rules, inherited from the relay's operational history
(.claude/skills/verify/SKILL.md):

* A hung probe child is ABANDONED, never killed — a killed mid-claim
  client wedges the relay for hours, right before the measurement the
  probe exists to protect. The orphan completes harmlessly or fails out
  on the relay's own clock.
* Backoff is BOUNDED and deterministic (exponential, capped): an
  unbounded retry loop against a wedged relay is just a slower hang.
"""

from __future__ import annotations

import dataclasses
import subprocess
import sys
import tempfile
import time


def probe_once(timeout_s: float) -> tuple[bool, str]:
    """Can a subprocess finish jax device discovery in time?

    On timeout the child is abandoned un-killed (module docs); its stderr
    tail rides the failure note — the relay error in it is what an
    operator needs to diagnose.
    """
    with tempfile.TemporaryFile() as err:
        child = subprocess.Popen(
            [sys.executable, "-c", "import jax; jax.devices()"],
            stdout=subprocess.DEVNULL, stderr=err,
        )

        def tail() -> str:
            err.seek(0)
            text = err.read().decode(errors="replace").strip()
            return f": ...{text[-160:]}" if text else ""

        try:
            rc = child.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            return False, ("TimeoutExpired: discovery hung; probe "
                           "abandoned un-killed" + tail())
        if rc == 0:
            return True, ""
        return False, f"probe exit {rc}" + tail()


def backoff_schedule(n: int, base_s: float = 2.0,
                     cap_s: float = 60.0) -> list[float]:
    """``n`` capped-exponential waits: base, 2·base, 4·base, ... ≤ cap."""
    return [min(cap_s, base_s * (2 ** i)) for i in range(max(0, n))]


@dataclasses.dataclass
class ProbeResult:
    ok: bool
    why: str  # last failure note ("" on success)
    attempts: int
    waited_s: float  # total backoff slept

    @property
    def degraded(self) -> bool:
        """The one boolean recorders put in their JSON line."""
        return not self.ok


def probe_devices(timeout_s: float, attempts: int = 1,
                  backoff_s: float = 2.0, cap_s: float = 60.0,
                  probe=probe_once, sleep=time.sleep) -> ProbeResult:
    """Probe device discovery up to ``attempts`` times with bounded
    exponential backoff between failures. ``probe``/``sleep`` are
    injectable for tests. Never raises: exhaustion is a normal outcome
    the caller answers with CPU degradation, not an exception."""
    attempts = max(1, int(attempts))
    waits = backoff_schedule(attempts - 1, backoff_s, cap_s)
    why = ""
    waited = 0.0
    for a in range(attempts):
        ok, why = probe(timeout_s)
        if ok:
            return ProbeResult(True, "", a + 1, waited)
        if a < len(waits):
            sleep(waits[a])
            waited += waits[a]
    return ProbeResult(False, why, attempts, waited)
