"""Fabric probe CLI.

Contract (reference ``2-network-params/mpi_send_recv.c:36-39``): one
``size,time`` CSV row per message size on stdout (µs per hop), consumable by
the reference's ``plot.ipynb`` α+βn analysis. ``--fit`` additionally prints
the fitted latency α (µs) and bandwidth 1/β (MB/s) to stderr, plus one
machine-readable ``{"metric": "pingpong_fit", ...}`` JSON line as the last
stdout line (``Fit.as_json`` schema).
"""

from __future__ import annotations

import argparse
import sys

from mpi_and_open_mp_tpu.apps._common import (
    add_platform_args, apply_platform_args, is_primary)
from mpi_and_open_mp_tpu.parallel import fabric, mesh as mesh_lib


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="mpi_and_open_mp_tpu.apps.pingpong")
    p.add_argument("--devices", type=int, default=None)
    p.add_argument("--reps", type=int, default=100)
    p.add_argument("--max-power", type=int, default=6,
                   help="probe sizes 10^0..10^k bytes (default 6)")
    p.add_argument("--out", default=None, help="also write CSV here")
    p.add_argument("--fit", action="store_true")
    add_platform_args(p)
    args = p.parse_args(argv)
    apply_platform_args(args)

    mesh = mesh_lib.make_mesh_1d(args.devices, axis="i")
    sizes = tuple(10**k for k in range(args.max_power + 1))
    rows = fabric.sweep(mesh, sizes=sizes, reps=args.reps)

    if is_primary():  # CSV-from-one-rank (mpi_send_recv.c:36-39 rank 0)
        print("size,time")
        for s, us in rows:
            print(f"{s},{us:.6f}")
        if args.out:
            fabric.write_csv(args.out, rows)
        if args.fit:
            import json

            fit = fabric.fit_alpha_beta(rows)
            print(fit.render(), file=sys.stderr)
            # Machine-readable twin of the stderr render, as the LAST
            # stdout line: harnesses take the CSV rows above verbatim and
            # json-parse this one (same tail-line discipline as bench.py).
            print(json.dumps({"metric": "pingpong_fit", **fit.as_json()}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
