"""Long-context attention CLI: drive the sequence-parallel layer.

The usable surface over ``parallel.context`` (ring + Ulysses attention) —
runs one forward pass of the chosen variant on an ``sp`` ring mesh,
verifies it against the single-device oracle (the same parity discipline
as the Life engine; skippable for oracle-infeasible lengths), and prints
elapsed seconds on stdout — the framework's standard timing contract
(cf. ``3-life/life_mpi.c:64-67``).
"""

from __future__ import annotations

import argparse
import functools
import sys
import time

import numpy as np

from mpi_and_open_mp_tpu.apps._common import (
    add_platform_args, apply_platform_args, is_primary)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="mpi_and_open_mp_tpu.apps.attention")
    p.add_argument("--variant", choices=("ring", "ulysses", "flash"),
                   default="ring",
                   help="sharded ring / sharded all-to-all / single-"
                   "device flash-chunked (no mesh)")
    p.add_argument("--seq", type=int, default=8192)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--head-dim", type=int, default=64)
    p.add_argument("--causal", action="store_true")
    p.add_argument("--grad", action="store_true",
                   help="time the backward pass too (both the chunked "
                   "path and the multi-device ring take a flash "
                   "custom_vjp backward, O(seq*d) residuals)")
    p.add_argument("--kv-heads", type=int, default=None,
                   help="GQA/MQA: fewer K/V heads than query heads")
    p.add_argument("--devices", type=int, default=None,
                   help="sp ring size (default: all local devices)")
    p.add_argument("--ring-layout", choices=("contiguous", "zigzag"),
                   default="contiguous",
                   help="ring variant only: zigzag = striped causal-"
                   "load-balanced token layout (the driver permutes "
                   "operands in and outputs back out, so the parity "
                   "check still runs in natural order)")
    p.add_argument("--dtype", choices=("float32", "bfloat16"),
                   default="bfloat16")
    p.add_argument("--no-check", action="store_true",
                   help="skip the oracle parity check (long sequences)")
    p.add_argument("--engine", choices=("auto", "jnp"), default="auto",
                   help="auto = on TPU, eligible shapes dispatch to the "
                   "bundled Pallas flash kernel; jnp = force the "
                   "chunked XLA engine (same as MOMP_TPU_FLASH=0)")
    p.add_argument("--seed", type=int, default=0)
    add_platform_args(p)
    args = p.parse_args(argv)
    apply_platform_args(args)

    import jax
    import jax.numpy as jnp

    from mpi_and_open_mp_tpu.parallel import context, mesh as mesh_lib

    if args.engine == "jnp":
        context.disable_tpu_flash()

    if args.variant == "flash":
        if args.devices not in (None, 1):
            p.error(f"--variant flash is single-device; --devices "
                    f"{args.devices} would be silently ignored (use "
                    "--variant ring/ulysses for a sharded run)")
        mesh = mesh_lib.make_mesh_1d(1, axis=context.AXIS_SP)  # size only

        def fn(q, k, v, mesh=None, causal=False):
            return context.flash_attention(q, k, v, causal=causal)
    else:
        mesh = mesh_lib.make_mesh_1d(args.devices, axis=context.AXIS_SP)
        fn = (context.ring_attention if args.variant == "ring"
              else context.ulysses_attention)
    zig = args.ring_layout != "contiguous"
    if zig:
        if args.variant != "ring":
            p.error("--ring-layout applies to --variant ring only")
        ring = fn

        def fn(q, k, v, mesh=None, causal=False):
            return ring(q, k, v, mesh=mesh, causal=causal, layout="zigzag")
    dtype = jnp.dtype(args.dtype)
    rng = np.random.default_rng(args.seed)
    hkv = args.kv_heads or args.heads
    q = jnp.asarray(
        rng.standard_normal((args.heads, args.seq, args.head_dim)), dtype)
    k, v = (jnp.asarray(
        rng.standard_normal((hkv, args.seq, args.head_dim)), dtype)
        for _ in range(2))
    qn, kn, vn = q, k, v  # natural order, for the oracle check
    if zig:
        # Pre-shard ONCE, outside the timed bracket — the zigzag order
        # is a deployment-time layout, not per-step work; timing the
        # permutes (plus their host sync) would bias exactly the
        # zigzag-vs-contiguous comparison this flag exists to make.
        pdev = mesh.shape[context.AXIS_SP]
        q, k, v = (context.zigzag_shard(x, pdev) for x in (q, k, v))

    from mpi_and_open_mp_tpu.utils.timing import anchor_sync

    if args.grad:
        def loss(q, k, v):
            o = fn(q, k, v, mesh=mesh, causal=args.causal)
            return jnp.sum(o.astype(jnp.float32) ** 2)

        # Jitted: an EAGER grad of the sharded variants hits a
        # "reshard non-addressable input" on multi-process meshes (the
        # internal device_put happens under the grad trace); under jit
        # the whole step stays in SPMD land — the pattern
        # tests/_dist_worker.py proves across real processes.
        run = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    else:
        run = functools.partial(fn, mesh=mesh, causal=args.causal)
    # All outputs (all three grads in --grad mode) must land before the
    # timer stops. fetch_all: jax.grad outputs come back SingleDeviceSharding
    # even on a mesh, and this is a timing bracket — one batched probe RTT
    # buys a guaranteed landing on the tunneled-TPU stack.
    sync = functools.partial(anchor_sync, fetch_all=True)

    sync(run(q, k, v))  # compile + warm
    t0 = time.perf_counter()
    result = run(q, k, v)
    sync(result)
    elapsed = time.perf_counter() - t0
    multiproc = jax.process_count() > 1
    if not args.no_check:
        # The parity operand: --grad timed the gradients, so a (single,
        # un-timed) forward supplies the checked output. Behind no_check
        # — the oracle-infeasible long-sequence mode — nothing here runs.
        out = (fn(q, k, v, mesh=mesh, causal=args.causal) if args.grad
               else result)
        if zig and not multiproc:
            # The zigzag output comes back in zigzag order; compare (and
            # report) in natural order, against the natural-order oracle.
            # (Multi-process: the un-permute would gather a
            # non-addressable global array — compare in zigzag order
            # instead, below.)
            out = context.zigzag_unshard(out, pdev)
        # The dense oracle wants one K/V head per query head — expand
        # GQA/MQA heads explicitly (the variants keep them un-expanded).
        groups = args.heads // hkv
        want = context.attention_reference(
            qn.astype(jnp.float32),
            jnp.repeat(kn.astype(jnp.float32), groups, axis=0),
            jnp.repeat(vn.astype(jnp.float32), groups, axis=0),
            causal=args.causal)
        if zig and multiproc:
            want = jnp.take(want, context.zigzag_order(args.seq, pdev),
                            axis=1)
        # On TPU, XLA's default matmul precision feeds the MXU bf16 even
        # for f32 operands, so differently-ordered reductions legitimately
        # diverge at the ~1e-3 level; only CPU f32 gets the tight bound.
        exact = dtype == jnp.float32 and jax.default_backend() != "tpu"
        tol = 1e-4 if exact else 0.06
        if multiproc:
            # Each process checks the shards it can address against the
            # matching slice of the (deterministic, same-seed) oracle —
            # then the errors are max-reduced ACROSS processes, so the
            # primary's verdict (and the timing line that follows it)
            # covers every shard, not just its own.
            from jax.experimental import multihost_utils

            want_np = np.asarray(want, np.float32)
            err = max((float(np.max(np.abs(
                np.asarray(s.data, np.float32) - want_np[s.index])))
                for s in out.addressable_shards), default=0.0)
            err = float(np.max(multihost_utils.process_allgather(
                np.float32(err))))
        else:
            err = float(np.max(np.abs(
                np.asarray(out, np.float32) - np.asarray(want))))
        if err > tol:
            print(f"PARITY FAIL: max|err|={err:.3g} > {tol}", file=sys.stderr)
            return 1
        if is_primary():
            print(f"parity ok (max|err|={err:.3g})", file=sys.stderr)

    # 2*(softmax QK^T)*V matmuls = 4*h*n^2*d multiply-adds (x0.5 causal).
    flops = 4 * args.heads * args.seq**2 * args.head_dim
    if args.causal:
        flops //= 2
    if is_primary():  # print-from-one-rank (3-life/life_mpi.c:64-67)
        print(f"{elapsed:.6f}")
        print(f"variant={args.variant} seq={args.seq} devices={mesh.size} "
              f"tflops={flops / elapsed / 1e12:.2f}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
