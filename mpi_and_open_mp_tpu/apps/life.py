"""Life driver CLI.

Contract (reference ``3-life/life_mpi.c:38-72``): positional ``.cfg``,
VTK snapshots under ``--outdir`` at the cfg's save cadence, and ONE line on
stdout — elapsed wall seconds of the timed step loop — so the reference's
``times.txt``/speedup-plot harness consumes TPU runs unchanged. The timer
brackets the whole simulate loop (saves included), like the reference's
``MPI_Wtime`` pair (``life_mpi.c:50,64``), but after a one-step compile
warm-up so XLA compilation isn't billed as simulation.
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys
import time

import numpy as np

import jax

from mpi_and_open_mp_tpu.apps._common import (
    add_platform_args, apply_platform_args, is_primary)
from mpi_and_open_mp_tpu.models.life import IMPLS, LAYOUTS, LifeSim
from mpi_and_open_mp_tpu.parallel import mesh as mesh_lib
from mpi_and_open_mp_tpu.robust.preempt import EXIT_PREEMPTED, Preempted
from mpi_and_open_mp_tpu.utils.config import load_config
from mpi_and_open_mp_tpu.utils.timing import append_times_txt


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mpi_and_open_mp_tpu.apps.life",
        description="Distributed Game of Life on a periodic torus (TPU backend)",
    )
    p.add_argument("cfg", help="board config file (steps/save_steps/nx ny/cells)")
    p.add_argument("--layout", choices=LAYOUTS, default="row")
    p.add_argument("--impl", choices=IMPLS, default="auto")
    p.add_argument("--fuse-steps", type=int, default=1, metavar="K",
                   help="halo depth: exchange once per K local steps")
    p.add_argument("--mesh", metavar="PY,PX",
                   help="explicit 2-D mesh shape (cart layout)")
    p.add_argument("--devices", type=int, metavar="N",
                   help="use only the first N devices (1-D layouts)")
    p.add_argument("--batch", type=int, default=0, metavar="B",
                   help="throughput mode: advance B stacked copies of the "
                        "cfg board in ONE device dispatch per segment "
                        "(batched LifeSim; needs --layout serial, excludes "
                        "snapshots/checkpoints/resume). The elapsed line "
                        "then covers B boards' worth of updates")
    p.add_argument("--serve", type=int, default=0, metavar="N",
                   help="serving mode: push N copies of the cfg board "
                        "through the fault-tolerant daemon (serve.daemon: "
                        "admission, bucket deadlines, retry/degrade "
                        "ladder). SIGTERM drains the in-flight batch, "
                        "checkpoints the queue under --checkpoint-dir, and "
                        "exits 75; --resume restores it. Prints the drain "
                        "wall seconds on the times.txt contract (first-"
                        "dispatch compile included — serving pays it too). "
                        "Needs --layout serial; --batch B caps the bucket "
                        "(default 8); excludes --outdir")
    p.add_argument("--outdir", default=None,
                   help="write VTK snapshots here (default: no saves)")
    p.add_argument("--times-file", default=None,
                   help="append elapsed seconds to this file (times.txt contract)")
    p.add_argument("--print-final-population", action="store_true")
    p.add_argument("--resume", action="store_true",
                   help="restart from the latest Orbax checkpoint in "
                        "--checkpoint-dir, else the latest VTK in --outdir")
    p.add_argument("--checkpoint-dir", default=None, metavar="DIR",
                   help="write an Orbax checkpoint at every save point "
                        "(sharded; no gather-to-root on multi-host)")
    p.add_argument("--checkpoint-every", type=int, default=0, metavar="N",
                   help="also checkpoint every N steps, independent of the "
                        "save cadence (preemption-safe restart points; "
                        "SIGTERM flushes one and exits 75)")
    p.add_argument("--plans", default=None, metavar="DIR",
                   help="durable tuned-plan store (default "
                        "$MOMP_TUNE_PLANS): records are validated + "
                        "parity-gated and installed BEFORE the first "
                        "dispatch, so a requeued exit-75 --resume run "
                        "restarts both warm and tuned; the resume status "
                        "line (stderr JSON) carries plan_source")
    p.add_argument("--profile", metavar="DIR", default=None,
                   help="capture a jax.profiler trace of the run into DIR")
    p.add_argument("--trace", metavar="PATH", default=None,
                   help="write obs span/event JSONL here (sets MOMP_TRACE; "
                        "read it back with analysis/trace_report.py)")
    p.add_argument("--debug-check", action="store_true",
                   help="assert halo-exchange consistency vs the oracle "
                        "before and after the run")
    add_platform_args(p)
    return p


def _find_latest(directory: str, pattern: str) -> tuple[str, int] | None:
    """Highest-step entry in ``directory`` matching ``pattern`` (one numeric
    group = the step index)."""
    import re

    if not directory or not os.path.isdir(directory):
        return None
    best = None
    for name in os.listdir(directory):
        m = re.fullmatch(pattern, name)
        if m:
            step = int(m.group(1))
            if best is None or step > best[1]:
                best = (os.path.join(directory, name), step)
    return best


def find_latest_snapshot(outdir: str) -> tuple[str, int] | None:
    """Latest ``life_NNNNNN.vtk`` in ``outdir`` and its step index."""
    return _find_latest(outdir, r"life_(\d{6,})\.vtk")


def find_latest_checkpoint(ckpt_dir: str) -> tuple[str, int] | None:
    """Latest ``step_NNNNNN`` Orbax checkpoint in ``ckpt_dir``."""
    return _find_latest(ckpt_dir, r"step_(\d{6,})")


def _plan_store(args):
    """The durable tuned-plan store named by ``--plans`` /
    ``MOMP_TUNE_PLANS``, or None (heuristics only)."""
    plans_dir = args.plans or os.environ.get("MOMP_TUNE_PLANS") or None
    if not plans_dir:
        return None
    from mpi_and_open_mp_tpu.tune.plans import PlanStore

    return PlanStore(plans_dir)


def _plan_fields(store, cfg, batch: int) -> dict:
    """The ``plan_source`` stamp for the resume status line: ``store``
    when the installed plans cover THIS (workload, stack shape) config,
    ``heuristic`` otherwise (no store, a miss, or ``MOMP_TUNE=0`` — the
    install was already skipped/quarantined upstream, so the lookup is
    honestly empty)."""
    fields = {"plan_source": "heuristic"}
    if store is None:
        return fields
    hit = store.lookup("life", (max(batch, 1), cfg.ny, cfg.nx))
    if hit is not None:
        fields["plan_source"] = "store"
        fields["tuned_path"] = hit["choice"]["path"]
    return fields


def make_mesh(args):
    if args.layout == "serial":
        return None
    if args.mesh:
        py, px = (int(v) for v in args.mesh.split(","))
        return mesh_lib.make_mesh_2d(py, px)
    if args.devices:
        axis = "x" if args.layout == "col" else "y"
        if args.layout == "cart":
            return mesh_lib.make_mesh_2d(*mesh_lib.dims_create(args.devices, 2))
        return mesh_lib.make_mesh_1d(args.devices, axis=axis)
    return None  # LifeSim default: all devices


def _serve(args, cfg, parser) -> int:
    """``--serve N``: the cfg board as N daemon requests.

    The times.txt line is the queue drain wall seconds; the service
    summary (resolved/shed/degraded, p99) goes to stderr so the
    reference harness still sees exactly one stdout number. Preemption
    follows the app contract: checkpoint (when ``--checkpoint-dir`` is
    set), stderr note, exit 75 for the queue loop's requeue.
    """
    from mpi_and_open_mp_tpu.obs import trace
    from mpi_and_open_mp_tpu.serve import ServePolicy, ServingDaemon

    ckpt = (os.path.join(args.checkpoint_dir, "serve_queue.state")
            if args.checkpoint_dir else None)
    policy = ServePolicy(max_batch=args.batch or 8,
                         max_depth=max(64, 2 * args.serve))
    # The daemon installs the store at construction, so EVERY resume
    # rung comes up tuned before the first dispatch (ROADMAP autotune
    # follow-on (c): a requeued exit-75 run restarts warm AND tuned).
    store = _plan_store(args)
    if args.resume:
        if not ckpt:
            parser.error("--serve --resume needs --checkpoint-dir")
        try:
            daemon = ServingDaemon.resume(ckpt, policy, plan_store=store)
        except ValueError as e:
            print(f"--serve --resume: {e}", file=sys.stderr)
            return 2
        print(f"resuming {daemon.queue.depth()} queued tickets from "
              f"{ckpt}", file=sys.stderr)
        print(json.dumps({
            "resumed": "serve_queue", "tickets": daemon.queue.depth(),
            **_plan_fields(store, cfg, policy.max_batch)}),
            file=sys.stderr)
    else:
        daemon = ServingDaemon(policy, checkpoint_path=ckpt,
                               plan_store=store)
    board = cfg.board()
    for _ in range(args.serve):
        daemon.submit(board, cfg.steps)
    t0 = time.perf_counter()
    try:
        with trace.span("life.serve", cfg=os.path.basename(args.cfg),
                        requests=args.serve, steps=cfg.steps):
            daemon.serve()
    except Preempted as e:
        print(f"{e} -- requeue with --serve --resume", file=sys.stderr)
        return EXIT_PREEMPTED
    elapsed = time.perf_counter() - t0
    if is_primary():
        print(f"{elapsed:.6f}")
        if args.times_file:
            append_times_txt(args.times_file, elapsed)
        s = daemon.summary()
        print(f"served {s['resolved']}/{s['requests']} "
              f"(shed {s['shed']}, degraded {s['degraded']}, "
              f"p99 {s['p99_latency_s']}s)", file=sys.stderr)
    return 0


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    apply_platform_args(args)
    if args.trace:
        # Before any sim work so every span of this run lands in the sink
        # (the sink is cached per env value; appends across invocations).
        os.environ["MOMP_TRACE"] = args.trace
    from mpi_and_open_mp_tpu.obs import trace

    cfg = load_config(args.cfg)
    serve_ckpt = (os.path.join(args.checkpoint_dir, "serve_queue.state")
                  if args.checkpoint_dir else None)
    if args.serve or (args.resume and serve_ckpt
                      and os.path.exists(serve_ckpt)):
        # Serving mode is its own driver: the daemon owns batching,
        # retries, and the queue checkpoint — the VTK path serialises
        # one simulation, so it's excluded at the CLI edge like --batch.
        # A bare --resume over a serve-queue checkpoint re-enters here
        # too (a requeued job must drain its tickets, not roll back to
        # an Orbax snapshot and silently drop them).
        if args.layout != "serial":
            parser.error("--serve needs --layout serial "
                         "(a bucket is one single-program dispatch)")
        if args.outdir:
            parser.error("--serve is a serving mode: drop --outdir")
        return _serve(args, cfg, parser)
    if args.batch:
        # Batched throughput mode maps straight onto the batched LifeSim
        # contract (models/life.py): serial layout only, and the VTK /
        # checkpoint paths serialise ONE board, so they're excluded at
        # the CLI edge rather than failing deeper in.
        if args.layout != "serial":
            parser.error("--batch needs --layout serial "
                         "(a batch is one single-program dispatch)")
        if args.outdir or args.checkpoint_dir or args.resume:
            parser.error("--batch is a throughput mode: drop --outdir/"
                         "--checkpoint-dir/--resume")
    kwargs = dict(
        layout=args.layout,
        impl=args.impl,
        mesh=make_mesh(args),
        fuse_steps=args.fuse_steps,
        outdir=args.outdir,
        checkpoint_dir=args.checkpoint_dir,
        checkpoint_every=args.checkpoint_every,
    )
    # Install tuned plans BEFORE the sim exists: the batched native
    # engines consult them per dispatch, so a --resume with --plans (or
    # MOMP_TUNE_PLANS in the queue loop's environment) restarts tuned,
    # not just warm. Install summary + the per-config plan_source ride
    # the stderr JSON status line the queue loop / tests read.
    store = _plan_store(args)
    plans_installed = store.install() if store is not None else None
    if args.resume:
        # Resume from whichever persisted state is newest (a stale
        # checkpoint dir must not roll back past newer VTK snapshots).
        ckpt = find_latest_checkpoint(args.checkpoint_dir)
        snap = find_latest_snapshot(args.outdir)
        if ckpt is not None and (snap is None or ckpt[1] >= snap[1]):
            path, step = ckpt
            print(f"resuming from checkpoint {path} (step {step})",
                  file=sys.stderr)
            sim = LifeSim.from_checkpoint(path, cfg, **kwargs)
        elif snap is not None:
            path, step = snap
            print(f"resuming from {path} (step {step})", file=sys.stderr)
            sim = LifeSim.from_snapshot(cfg, path, step, **kwargs)
        else:
            sources = [f"no snapshots in {args.outdir!r}"]
            if args.checkpoint_dir is not None:
                sources.insert(
                    0, f"no checkpoints in {args.checkpoint_dir!r}"
                )
            print(f"--resume: {' and '.join(sources)}", file=sys.stderr)
            return 2
        print(json.dumps({
            "resumed": os.path.basename(path), "step": step,
            **({"plans_installed": plans_installed.get("installed", 0)}
               if plans_installed is not None else {}),
            **_plan_fields(store, cfg, args.batch)}), file=sys.stderr)
    elif args.batch:
        # B stacked copies of the cfg board: cups is content-independent
        # for a dense stencil, so identical copies time exactly what B
        # distinct requests would.
        stack = np.stack([cfg.board()] * args.batch)
        sim = LifeSim(cfg, initial_board=stack, **kwargs)
    else:
        sim = LifeSim(cfg, **kwargs)
    # Warm-up: compile every stepper run() will hit, on THIS instance (jit
    # caches are per-instance and keyed on the static step count), so no
    # XLA compilation lands inside the timed bracket.
    sim.warmup()
    if args.debug_check:
        sim.debug_check()

    if args.profile:
        import jax

        ctx = jax.profiler.trace(args.profile)
    else:
        ctx = contextlib.nullcontext()
    with ctx:
        t0 = time.perf_counter()
        try:
            # The whole-run root span: segments/advances nest under it; a
            # Preempted exit closes it with an error attr, so the trace
            # still shows how far the run got.
            with trace.span(
                "life.run",
                cfg=os.path.basename(args.cfg),
                steps=cfg.steps,
                impl=sim.impl,
                layout=sim.layout,
            ):
                final = sim.run()  # collect() inside forces completion
        except Preempted as e:
            # EX_TEMPFAIL: the queue keeps the job; --resume continues
            # from the flushed checkpoint (docs/MIGRATION.md workflow).
            print(f"{e} -- requeue with --resume", file=sys.stderr)
            return EXIT_PREEMPTED
        elapsed = time.perf_counter() - t0
    if args.debug_check:
        sim.debug_check()

    # One process owns stdout and the times file — the reference's
    # print-from-one-rank discipline (3-life/life_mpi.c:64-67).
    if is_primary():
        print(f"{elapsed:.6f}")
        if args.times_file:
            append_times_txt(args.times_file, elapsed)
        if args.print_final_population:
            print(int(np.asarray(final).sum()), file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
