"""Life driver CLI.

Contract (reference ``3-life/life_mpi.c:38-72``): positional ``.cfg``,
VTK snapshots under ``--outdir`` at the cfg's save cadence, and ONE line on
stdout — elapsed wall seconds of the timed step loop — so the reference's
``times.txt``/speedup-plot harness consumes TPU runs unchanged. The timer
brackets the whole simulate loop (saves included), like the reference's
``MPI_Wtime`` pair (``life_mpi.c:50,64``), but after a one-step compile
warm-up so XLA compilation isn't billed as simulation.
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

import jax

from mpi_and_open_mp_tpu.models.life import IMPLS, LAYOUTS, LifeSim
from mpi_and_open_mp_tpu.parallel import mesh as mesh_lib
from mpi_and_open_mp_tpu.utils.config import load_config
from mpi_and_open_mp_tpu.utils.timing import append_times_txt


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mpi_and_open_mp_tpu.apps.life",
        description="Distributed Game of Life on a periodic torus (TPU backend)",
    )
    p.add_argument("cfg", help="board config file (steps/save_steps/nx ny/cells)")
    p.add_argument("--layout", choices=LAYOUTS, default="row")
    p.add_argument("--impl", choices=IMPLS, default="auto")
    p.add_argument("--fuse-steps", type=int, default=1, metavar="K",
                   help="halo depth: exchange once per K local steps")
    p.add_argument("--mesh", metavar="PY,PX",
                   help="explicit 2-D mesh shape (cart layout)")
    p.add_argument("--devices", type=int, metavar="N",
                   help="use only the first N devices (1-D layouts)")
    p.add_argument("--outdir", default=None,
                   help="write VTK snapshots here (default: no saves)")
    p.add_argument("--times-file", default=None,
                   help="append elapsed seconds to this file (times.txt contract)")
    p.add_argument("--print-final-population", action="store_true")
    return p


def make_mesh(args):
    if args.layout == "serial":
        return None
    if args.mesh:
        py, px = (int(v) for v in args.mesh.split(","))
        return mesh_lib.make_mesh_2d(py, px)
    if args.devices:
        axis = "x" if args.layout == "col" else "y"
        if args.layout == "cart":
            return mesh_lib.make_mesh_2d(*mesh_lib.dims_create(args.devices, 2))
        return mesh_lib.make_mesh_1d(args.devices, axis=axis)
    return None  # LifeSim default: all devices


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    cfg = load_config(args.cfg)
    sim = LifeSim(
        cfg,
        layout=args.layout,
        impl=args.impl,
        mesh=make_mesh(args),
        fuse_steps=args.fuse_steps,
        outdir=args.outdir,
    )
    # Warm-up: compile every stepper run() will hit, on THIS instance (jit
    # caches are per-instance and keyed on the static step count), so no
    # XLA compilation lands inside the timed bracket.
    sim.warmup()

    t0 = time.perf_counter()
    final = sim.run()  # collect() inside forces device completion
    elapsed = time.perf_counter() - t0

    print(f"{elapsed:.6f}")
    if args.times_file:
        append_times_txt(args.times_file, elapsed)
    if args.print_final_population:
        print(int(np.asarray(final).sum()), file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
