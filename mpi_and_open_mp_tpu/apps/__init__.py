"""Command-line drivers — the reference's L3 ``main()`` layer.

Each app keeps the reference's IO contract (positional ``.cfg``/N argument,
bare elapsed-seconds on stdout so ``times.txt`` harnesses keep working) and
adds a real argparse CLI for mesh/layout/impl selection:

* ``python -m mpi_and_open_mp_tpu.apps.life <cfg>``      ≙ ``life_mpi`` / ``life_cart`` / ``life2d``
* ``python -m mpi_and_open_mp_tpu.apps.integral <N>``    ≙ ``mpi_integral``
* ``python -m mpi_and_open_mp_tpu.apps.pingpong``        ≙ ``mpi_send_recv``
* ``python -m mpi_and_open_mp_tpu.apps.attention``       — beyond-reference: the
  long-context sequence-parallel layer (``parallel.context``) as a driver
* ``python -m mpi_and_open_mp_tpu.apps.hello``           ≙ ``hello_world`` / ``send``
"""
