"""Shared CLI plumbing: platform selection and multi-host bootstrap.

The reference's process bootstrap is ``MPI_Init`` under ``mpirun``
(``0-intro/hello_world.c:8``); here it splits into two knobs:

* ``--distributed``: ``jax.distributed.initialize()`` — multi-host pod
  bootstrap. Coordinator/rank come from ``--coordinator``/
  ``--num-processes``/``--process-id`` (or the ``JOB_COORDINATOR``/
  ``JOB_NUM_PROCS``/``JOB_PROC_ID`` environment the ``launchers/job_*.sh``
  scripts export, the way ``mpirun``/PBS exported ranks for the reference
  — ``job_life.sh:2-8``); with none of them set, JAX's own cluster
  auto-detection runs (SLURM, GKE, ...).
* ``--virtual-devices N``: run on N virtual CPU devices (XLA host-platform
  device count), which is how scaling sweeps and tests exercise multi-chip
  code paths on a single host. Must be applied before any JAX device use;
  the environment's sitecustomize pins jax_platforms to the TPU plugin, so
  this re-pins to cpu explicitly.

Multi-process output discipline: exactly one process owns stdout/file
artifacts (:func:`is_primary`), the reference's write-from-one-rank rule
(``3-life/life_mpi.c:54-57`` — there it is rank size-1; here process 0).
"""

from __future__ import annotations

import argparse
import os


def add_platform_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument(
        "--virtual-devices", type=int, default=None, metavar="N",
        help="simulate N devices on CPU (scaling studies without a pod)",
    )
    parser.add_argument(
        "--distributed", action="store_true",
        help="multi-host bootstrap via jax.distributed.initialize()",
    )
    parser.add_argument(
        "--coordinator", metavar="HOST:PORT", default=None,
        help="explicit coordinator for --distributed "
             "(default: $JOB_COORDINATOR, else JAX cluster auto-detection)",
    )
    parser.add_argument(
        "--num-processes", type=int, default=None, metavar="N",
        help="process count for --distributed (default: $JOB_NUM_PROCS)",
    )
    parser.add_argument(
        "--process-id", type=int, default=None, metavar="I",
        help="this process's rank for --distributed (default: $JOB_PROC_ID)",
    )


def apply_platform_args(args) -> None:
    import jax

    if args.distributed:
        if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
            # Honour an explicit cpu ask (the local multi-process stand-in
            # for a DCN pod): the sitecustomize pins the TPU plugin at
            # interpreter start regardless of the environment, so the env
            # var alone is not enough.
            jax.config.update("jax_platforms", "cpu")
        # Flags beat the JOB_* environment; anything still unset stays
        # None, which jax.distributed.initialize fills via its own
        # cluster auto-detection (SLURM, GKE, ...).
        env = os.environ.get
        coord = args.coordinator or env("JOB_COORDINATOR")
        nprocs = (args.num_processes if args.num_processes is not None
                  else int(env("JOB_NUM_PROCS", 0)) or None)
        proc_id = (args.process_id if args.process_id is not None
                   else (int(env("JOB_PROC_ID"))
                         if env("JOB_PROC_ID") is not None else None))
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=nprocs,
            process_id=proc_id,
        )
    if args.virtual_devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.virtual_devices}"
        )
        jax.config.update("jax_platforms", "cpu")


def is_primary() -> bool:
    """True in the process that owns stdout/artifact writes (process 0;
    trivially true un-distributed)."""
    import jax

    return jax.process_index() == 0
