"""Shared CLI plumbing: platform selection and multi-host bootstrap.

The reference's process bootstrap is ``MPI_Init`` under ``mpirun``
(``0-intro/hello_world.c:8``); here it splits into two knobs:

* ``--distributed``: ``jax.distributed.initialize()`` — multi-host pod
  bootstrap, coordinator/rank discovered from the environment the way
  ``mpirun``/PBS exported ranks for the reference (``job_life.sh:2-8``).
* ``--virtual-devices N``: run on N virtual CPU devices (XLA host-platform
  device count), which is how scaling sweeps and tests exercise multi-chip
  code paths on a single host. Must be applied before any JAX device use;
  the environment's sitecustomize pins jax_platforms to the TPU plugin, so
  this re-pins to cpu explicitly.
"""

from __future__ import annotations

import argparse
import os


def add_platform_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument(
        "--virtual-devices", type=int, default=None, metavar="N",
        help="simulate N devices on CPU (scaling studies without a pod)",
    )
    parser.add_argument(
        "--distributed", action="store_true",
        help="multi-host bootstrap via jax.distributed.initialize()",
    )


def apply_platform_args(args) -> None:
    import jax

    if args.distributed:
        jax.distributed.initialize()
    if args.virtual_devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.virtual_devices}"
        )
        jax.config.update("jax_platforms", "cpu")
