"""Quadrature driver CLI.

Contract (reference ``1-integral/integral.c:9-60``): positional N, elapsed
seconds on stdout. The value itself is printed only with ``--print-value``
(the reference comments its value printf out, ``integral.c:27,44``). N is
int64 — the reference's 32-bit ``atoi`` truncation (``integral.c:12``) is
deliberately not reproduced; pass ``--truncate-32bit`` to mimic it when
comparing against recorded reference timings.
"""

from __future__ import annotations

import argparse
import sys
import time

from mpi_and_open_mp_tpu.apps._common import (
    add_platform_args, apply_platform_args, is_primary)
from mpi_and_open_mp_tpu.models.integral import Integral
from mpi_and_open_mp_tpu.parallel import mesh as mesh_lib
from mpi_and_open_mp_tpu.utils.timing import append_times_txt


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="mpi_and_open_mp_tpu.apps.integral")
    p.add_argument("n", type=int, help="number of trapezoids")
    p.add_argument("--devices", type=int, default=None)
    p.add_argument("--print-value", action="store_true")
    p.add_argument("--truncate-32bit", action="store_true",
                   help="reproduce the reference's unsigned-32-bit N overflow")
    p.add_argument("--times-file", default=None)
    add_platform_args(p)
    args = p.parse_args(argv)
    apply_platform_args(args)

    n = args.n
    if args.truncate_32bit:
        n = n % (1 << 32)
    mesh = mesh_lib.make_mesh_1d(args.devices, axis="i") if args.devices else None
    integral = Integral(n, mesh=mesh)
    integral.compute()  # warm-up: compile outside the timed region

    t0 = time.perf_counter()
    value = integral.compute()
    elapsed = time.perf_counter() - t0

    if is_primary():  # print-from-one-rank (1-integral/integral.c:45-46)
        print(f"{elapsed:.6f}")
        if args.times_file:
            append_times_txt(args.times_file, elapsed)
        if args.print_value:
            print(f"{value!r}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
