"""SPMD bootstrap + ring messaging demo.

Reference parity: ``0-intro/hello_world.c`` (init, print size/rank) and
``0-intro/send.c`` (each rank sends a greeting to ``(r+1)%size`` and
receives from ``(r-1+size)%size``). The TPU equivalents: device/process
enumeration via ``jax.devices``/``jax.process_index``, and a one-hop ring
``lax.ppermute`` carrying each device's token to its successor — the same
ring pattern, minus the blocking-send deadlock hazard.
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

from mpi_and_open_mp_tpu.apps._common import add_platform_args, apply_platform_args


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="mpi_and_open_mp_tpu.apps.hello")
    p.add_argument("--devices", type=int, default=None)
    add_platform_args(p)
    args = p.parse_args(argv)
    apply_platform_args(args)

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from mpi_and_open_mp_tpu.parallel import mesh as mesh_lib
    from mpi_and_open_mp_tpu.parallel.halo import ring_perm

    n = args.devices or len(jax.devices())
    print(f"process {jax.process_index()} of {jax.process_count()}; "
          f"{n} device(s): {[d.device_kind for d in jax.devices()[:n]]}")

    mesh = mesh_lib.make_mesh_1d(n, axis="r")
    tokens = jax.device_put(
        jnp.arange(n, dtype=jnp.int32), NamedSharding(mesh, P("r"))
    )
    received = mesh_lib.shard_map(
        lambda t: jax.lax.ppermute(t, "r", ring_perm(n, 1)),
        mesh=mesh, in_specs=P("r"), out_specs=P("r"),
    )(tokens)
    for i, src in enumerate(np.asarray(jax.device_get(received))):
        print(f"device {i} received hello from device {int(src)}")
    ok = np.array_equal(
        np.asarray(jax.device_get(received)), np.roll(np.arange(n), 1)
    )
    print("ring ok" if ok else "ring BROKEN")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
