"""Candidate space enumeration: every LEGAL plan for one configuration.

Every performance-critical decision in the stack is a static heuristic
today — the bitsliced gate (``BITSLICE_MIN_BATCH``, VMEM floors), the
cell-packed batch ladder, the roll-vs-Pallas stencil dispatch, the
pow2-vs-plane-32 bucket rounding, the row decomposition. PAPERS.md
"Efficient Process-to-Node Mapping Algorithms for Stencil Computations"
shows the right choice is workload- and topology-dependent; this module
enumerates the choices so ``tune.runner`` can MEASURE them instead.

A :class:`Candidate` names one complete plan: the engine path, the pack
layout it implies, the batch-bucket rounding the serve layer should use
for it, and the decomposition axis order. Enumeration is *legality*
filtered — a candidate is listed only if this process could actually
dispatch it (VMEM fits, backend support, channel-count support), so the
runner never wastes profile budget on a path that cannot run, and the
heuristic's own choice is always in the list (which is what makes the
measured ``vs_heuristic`` ratio >= 1.0 by construction).

Axis order is enumerated from the topology (single-device profiling
covers ``"row"`` only — the repo's decomposition; multi-device meshes
add ``"col"`` as a future profile axis), and the runner profiles only
what a single process can honestly time.
"""

from __future__ import annotations

import dataclasses

#: Bucket-rounding vocabulary: the serve batcher pads bitsliced-eligible
#: buckets to 32-board plane multiples and everything else to the pow2
#: ladder (``serve.batcher.bucket_batch_size``).
BUCKET_PLANE32 = "plane32"
BUCKET_POW2 = "pow2"


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One complete tunable plan for a (workload, stack shape) pair."""

    workload: str
    #: Engine path: the ``native_path_batch`` vocabulary for life
    #: (``bitsliced``/``vmem``/``vmem-grid``/``fused``/``frame``/
    #: ``xla``) or ``stencil:roll``/``stencil:pallas`` for other specs.
    path: str
    #: ``bitsliced`` / ``cell-packed`` for life, ``-`` for stencil paths.
    pack_layout: str
    #: Batch-bucket rounding the path wants (plane32 iff bitsliced).
    bucket_rounding: str
    #: Decomposition axis order (single-process profiling: "row").
    axis_order: str = "row"
    #: Halo schedule for sharded candidates ("overlap"/"seq", or
    #: "sparse" for the ``sparse_sharded:*`` active-tile paths, which
    #: decide exchange-vs-skip per round); "-" for single-device paths,
    #: where there is no exchange to schedule.
    halo_overlap: str = "-"
    #: Interior fuse depth for sharded candidates: steps fused per ghost
    #: round (ghost depth = ``fuse_steps * radius``). 1 everywhere else.
    fuse_steps: int = 1
    #: Boundary sub-round depth for sharded overlap candidates —
    #: ``== fuse_steps`` is the coupled one-exchange round, a smaller
    #: divisor partitions each edge strip into per-edge sub-exchanges
    #: (deeper interior, shallower edges — arxiv 2508.13370).
    boundary_steps: int = 1


#: Tile edge the sparse-sharded candidates profile at — one fixed rung
#: (PR 13's sweep showed tile choice is second-order next to the
#: sparse-vs-dense decision itself, which is what the race measures).
SPARSE_SHARDED_TILE = 64

#: The sparse-sharded engine's static fuse depth — the ctor default it
#: shipped with. PR 20 promotes fuse to an enumerated axis; this rung
#: (clamped by legality like the ctor clamps it) is always candidate
#: #0 of the sparse slate so ``vs_heuristic`` stays >= 1.0.
SPARSE_FUSE_HEURISTIC = 16


def sparse_fuse_depths(radius: int, tile: int) -> tuple[int, ...]:
    """Legal sparse-sharded fuse depths, heuristic rung FIRST. Legality
    is the ctor's clamp: ``radius * fuse <= tile`` (a deeper fuse would
    read past one tile's halo ring). The heuristic depth 16 is clamped
    the same way the engine clamps it, so the first rung is exactly
    what an untuned ctor runs; ``MOMP_TUNE_SPARSE_FUSE`` (comma list,
    default "4,16,64") adds the measured rungs — wide-radius specs,
    where the clamp bites hardest, are exactly why this axis exists."""
    import os

    cap = max(1, int(tile) // max(1, int(radius)))
    heur = min(SPARSE_FUSE_HEURISTIC, cap)
    raw = os.environ.get("MOMP_TUNE_SPARSE_FUSE", "4,16,64")
    out = [heur]
    for tok in raw.split(","):
        if not tok.strip():
            continue
        f = max(1, int(tok))
        if f <= cap and f not in out:
            out.append(f)
    return tuple(out)


def sharded_fuse_depths() -> tuple[int, ...]:
    """Interior fuse depths the sharded space enumerates.
    ``MOMP_TUNE_FUSE_DEPTHS`` (comma list) overrides the default
    ``(1, 2)`` — the r08 chip queue sweeps deeper rungs where exposed
    transfer makes depth worth buying; the CPU default keeps the tuner
    pass bounded. Depth 1 (the coupled heuristic's rung) is always
    included so the heuristic stays in the race."""
    import os

    raw = os.environ.get("MOMP_TUNE_FUSE_DEPTHS", "1,2")
    depths = sorted({max(1, int(tok)) for tok in raw.split(",") if tok})
    return tuple(depths) if 1 in depths else (1, *depths)


def _boundary_depths(fuse_steps: int) -> tuple[int, ...]:
    """Legal boundary sub-round depths for one interior depth: every
    divisor, coupled (``== fuse_steps``) first so the one-exchange round
    opens each depth's slate."""
    return tuple(b for b in range(fuse_steps, 0, -1)
                 if fuse_steps % b == 0)


def axis_orders(device_count: int = 1,
                mesh_axes: tuple[int, int] | None = None) -> tuple[str, ...]:
    """Legal decomposition axis orders for a topology. One device has
    exactly one (nothing to decompose); multi-device meshes add the
    column order, and a REAL 2-D mesh (both axis sizes > 1 — pass
    ``mesh_axes``) adds the Cartesian block order, the axis PAPERS.md's
    process-mapping result actually varies."""
    if int(device_count) <= 1:
        return ("row",)
    orders = ("row", "col")
    if mesh_axes is not None:
        py, px = (int(a) for a in mesh_axes)
        if py > 1 and px > 1:
            orders = ("row", "col", "cart")
    return orders


def sharded_candidates(workload: str, shape: tuple[int, int],
                       mesh) -> list[Candidate]:
    """Every legal sharded-halo candidate for (workload, BOARD shape)
    on ``mesh``: axis order x halo schedule, legality-filtered the same
    way the batched space is — a layout is listed only if the board
    divides the mesh under it AND the mesh actually shards that layout's
    axes (a 1-D y mesh lists no "col"/"cart": they would shard nothing),
    and the "overlap" leg only where the persistent plan accepts the
    geometry (``parallel.haloplan``; the "seq" leg is always legal, so
    the historic schedule is always in the race — the sharded twin of
    heuristic-first). Single-channel workloads additionally list the
    ``sparse_sharded:<layout>`` active-tile path where its plan accepts
    the geometry (tile divides the shard, ``MOMP_SPARSE_SHARDED`` not
    killed) — the dense legs are enumerated FIRST, so the heuristic
    stays in the race and a sparse candidate only wins by measurement
    (on the tuner's dense random boards it falls to the crossover rung
    and loses, which is the honest answer)."""
    from mpi_and_open_mp_tpu.parallel import haloplan
    from mpi_and_open_mp_tpu import stencils
    from mpi_and_open_mp_tpu.stencils import engine as stencil_engine
    from mpi_and_open_mp_tpu.stencils import sparse_sharded

    spec = stencils.get(workload)
    ny, nx = (int(x) for x in shape)
    mesh_axes = (mesh.shape.get("y", 1), mesh.shape.get("x", 1))
    out = []
    for layout in axis_orders(mesh.size, mesh_axes):
        py, px = stencil_engine.mesh_axes_for(layout, mesh)
        if py * px <= 1 or ny % py or nx % px:
            continue
        shard = (ny // py, nx // px)
        if not stencil_engine.fused_steps_valid(spec, shard, 1):
            continue
        plan = haloplan.plan_halo(layout, (py, px), shard, spec.radius, 1,
                                  channels=spec.channels)
        schedules = ("overlap", "seq") if plan.overlap else ("seq",)
        for sched in schedules:
            out.append(Candidate(
                workload=str(workload), path=f"sharded:{layout}",
                pack_layout="-", bucket_rounding=BUCKET_POW2,
                axis_order=layout, halo_overlap=sched))
        # Interior depth x boundary depth, enumerated independently:
        # deeper interiors amortise the exchange, shallower boundaries
        # partition it into per-edge sub-sends. Each pair is legality-
        # gated by the persistent plan itself (a depth that empties the
        # interior degrades to seq and is not re-listed). Depth (1, 1)
        # — the coupled heuristic — is already listed above, so
        # ``vs_heuristic`` stays >= 1.0 by construction.
        if plan.overlap:
            for k in sharded_fuse_depths():
                if not stencil_engine.fused_steps_valid(spec, shard, k):
                    continue
                for b in _boundary_depths(k):
                    if (k, b) == (1, 1):
                        continue
                    pk = haloplan.plan_halo(
                        layout, (py, px), shard, spec.radius, k,
                        boundary_steps=b, channels=spec.channels)
                    if not pk.overlap:
                        continue
                    out.append(Candidate(
                        workload=str(workload),
                        path=f"sharded:{layout}",
                        pack_layout="-", bucket_rounding=BUCKET_POW2,
                        axis_order=layout, halo_overlap="overlap",
                        fuse_steps=k, boundary_steps=b))
        if spec.channels == 1:
            sp = sparse_sharded.plan_sparse_sharded(
                layout, (py, px), shard, spec.radius,
                SPARSE_SHARDED_TILE)
            if sp.enabled:
                # Fuse is an enumerated axis (PR 20): the clamped
                # ctor-default depth leads so the untuned engine is
                # always candidate #0 of the sparse slate.
                for f in sparse_fuse_depths(spec.radius,
                                            SPARSE_SHARDED_TILE):
                    out.append(Candidate(
                        workload=str(workload),
                        path=f"sparse_sharded:{layout}",
                        pack_layout="-", bucket_rounding=BUCKET_POW2,
                        axis_order=layout, halo_overlap="sparse",
                        fuse_steps=f))
    return out


def life_paths(shape: tuple[int, int, int], on_tpu: bool) -> list[str]:
    """Every batched life engine path this process can LEGALLY dispatch
    for ``shape`` — the heuristic's pick is always among them. Unlike
    the heuristic, the bitsliced candidate ignores ``BITSLICE_MIN_BATCH``
    (the gate boundary is exactly what the tuner exists to re-measure);
    hard gates (VMEM fits, the ``MOMP_BITSLICE`` kill switch, backend
    support) stay binding."""
    from mpi_and_open_mp_tpu.ops import bitlife, pallas_life

    b, ny, nx = (int(x) for x in shape)
    paths = []
    if pallas_life._BITSLICE and bitlife.fits_vmem_bitsliced((b, ny, nx)):
        paths.append("bitsliced")
    if on_tpu:
        if bitlife.fits_vmem_packed_batch((b, ny, nx)):
            paths.append("vmem")
        if bitlife.fits_vmem_packed((ny, nx)):
            paths.append("vmem-grid")
        if bitlife.fused_bits_supported((ny, nx)):
            paths.append("fused")
        if bitlife.plan_sharded_bits((ny, nx), 1, 1, False, False) is not None:
            paths.append("frame")
    paths.append("xla")
    return paths


def stencil_paths(spec, shape: tuple[int, int, int]) -> list[str]:
    """Legal batched engine paths for a non-life stencil spec: the
    vmapped roll engine always, plus the per-spec Pallas padded kernel
    when the spec supports a batch axis (single-channel only — see
    ``stencils.engine.pallas_batch_supported``), plus the PR 20 engine
    families where their legality gates pass — separable needs a
    factorizable table (``separable_supported``: rank <= radius, which
    no radius-1 zero-center table satisfies, so narrow specs enumerate
    exactly as before), FFT needs a float dtype and radius >=
    ``FFT_MIN_RADIUS``. Both respect the ``MOMP_ENGINE_FAMILY`` pin."""
    from mpi_and_open_mp_tpu.stencils import engine as stencil_engine

    paths = ["stencil:roll"]
    if stencil_engine.pallas_batch_supported(spec, shape):
        paths.append("stencil:pallas")
    if (stencil_engine.separable_supported(spec)
            and stencil_engine.family_allowed("sep")):
        paths.append("stencil:sep")
    if (stencil_engine.fft_supported(spec)
            and stencil_engine.family_allowed("fft")):
        paths.append("stencil:fft")
    return paths


def pack_layout_for(path: str) -> str:
    if path == "bitsliced":
        return "bitsliced"
    if path.startswith("stencil:"):
        return "-"
    return "cell-packed"


def bucket_rounding_for(path: str) -> str:
    return BUCKET_PLANE32 if path == "bitsliced" else BUCKET_POW2


def heuristic_path(workload: str, shape: tuple[int, int, int],
                   on_tpu: bool) -> str:
    """The path the STATIC heuristics would pick today — the baseline
    every tuned plan is measured against. Computed with any installed
    plan pinned OUT, so tuning never grades itself against itself."""
    from mpi_and_open_mp_tpu.ops import pallas_life

    if workload == "life":
        with pallas_life._planned_pinned(workload, shape, None):
            return pallas_life.native_path_batch(tuple(shape), on_tpu=on_tpu)
    return "stencil:roll"


def candidates(workload: str, shape: tuple[int, int, int], *,
               on_tpu: bool | None = None,
               device_count: int = 1) -> list[Candidate]:
    """Every legal candidate for (workload, stack shape, topology),
    heuristic-first (ties in the runner's argmin then keep the
    heuristic, so plans only move when a candidate measurably wins)."""
    import jax

    if on_tpu is None:
        on_tpu = jax.default_backend() == "tpu"
    if workload == "life":
        paths = life_paths(shape, on_tpu)
    else:
        from mpi_and_open_mp_tpu import stencils

        paths = stencil_paths(stencils.get(workload), shape)
    heur = heuristic_path(workload, shape, on_tpu)
    if heur in paths:
        paths = [heur] + [p for p in paths if p != heur]
    out = []
    for axis in axis_orders(device_count):
        for p in paths:
            out.append(Candidate(
                workload=str(workload), path=p,
                pack_layout=pack_layout_for(p),
                bucket_rounding=bucket_rounding_for(p),
                axis_order=axis))
    return out


def runner_for(workload: str, path: str):
    """The callable ``(stack_jnp, n) -> stack_jnp`` that dispatches one
    candidate path directly (bypassing the heuristic dispatcher, which
    would re-plan). Raises ``ValueError`` on an unknown path so a stale
    plan record can never silently run the wrong engine."""
    from mpi_and_open_mp_tpu.ops import bitlife, pallas_life

    if workload == "life":
        interp = pallas_life._interpret()
        if path == "bitsliced":
            return lambda s, n: bitlife.life_run_bitsliced_batch(
                s, n, interpret=interp)
        if path in ("vmem", "vmem-grid"):
            return lambda s, n: bitlife.life_run_vmem_bits_batch(
                s, n, interpret=interp, resident=(path == "vmem"))
        if path == "fused":
            return bitlife.life_run_fused_bits_batch
        if path == "frame":
            return bitlife.life_run_frame_bits_batch
        if path == "xla":
            return bitlife.life_run_bits_xla_batch
        raise ValueError(f"unknown life engine path {path!r}")
    from mpi_and_open_mp_tpu import stencils
    from mpi_and_open_mp_tpu.stencils import engine as stencil_engine

    spec = stencils.get(workload)
    if path == "stencil:roll":
        return lambda s, n: stencils.run_roll_batch(spec, s, n)
    if path == "stencil:pallas":
        return lambda s, n: stencil_engine.run_padded_pallas_batch(
            spec, s, n)
    if path in ("stencil:sep", "stencil:fft"):
        family = stencil_engine.family_for_path(path)
        return lambda s, n: stencil_engine.run_family_batch(
            spec, s, n, family)
    raise ValueError(f"unknown stencil engine path {path!r} "
                     f"for workload {workload!r}")
