"""Measured candidate profiling with the repo's timing discipline.

Nothing here trusts a model: every candidate is DISPATCHED, parity-gated
against the NumPy oracle FIRST (an engine that cannot reproduce the rule
may never win, however fast), then timed with the same chained-dispatch
differencing every recorded number in ``results/`` uses — two run
lengths through one compiled program (``n`` is a runtime scalar on every
engine), steady per-step cost = the difference over the extra steps, so
the ~70 ms host<->device RTT and the fixed dispatch overhead cancel.
Profiling flows through ``obs/`` (a ``tune.candidate`` span per timed
candidate, ``tune.candidate`` status counters), so a tuning pass is as
observable as a serve window.

The heuristic's own choice is always candidate #0 and ties keep it
(strict ``<`` to dethrone), which makes the reported ``vs_heuristic``
ratio >= 1.0 by construction: tuned never loses to the heuristic it
replaces, because the heuristic is in the race.
"""

from __future__ import annotations

import time

import numpy as np

from mpi_and_open_mp_tpu.tune import plans as plans_mod
from mpi_and_open_mp_tpu.tune import space

_TUNE_SEED = 46


def _build_stack(spec, shape) -> np.ndarray:
    b, ny, nx = shape
    rng = np.random.default_rng(_TUNE_SEED)
    return np.stack([spec.init(rng, (ny, nx)) for _ in range(b)]).astype(
        spec.np_dtype)


def tune(workload: str, shape, *, steps: int = 64, store=None,
         reps: int = 2, mult: int = 5,
         parity_steps: int = plans_mod.PARITY_STEPS) -> dict:
    """One bounded tuning pass for (workload, stack shape): enumerate
    legal candidates, parity-gate each, time the survivors, install the
    winner in-process, and (with ``store``) persist it as a
    ``momp-plan/1`` record — for life, exporting the winner's bucket
    executable into the SAME store directory under the SAME digest, so
    the next process deserializes instead of retracing.

    ``steps`` is the short bracket; the long bracket is ``steps *
    mult`` and the steady per-step cost is their difference over the
    extra steps (falling back to the short bracket when differencing is
    ill-conditioned, same as ``bench._batched_phase``)."""
    import jax
    import jax.numpy as jnp

    from mpi_and_open_mp_tpu import stencils
    from mpi_and_open_mp_tpu.obs import metrics, trace
    from mpi_and_open_mp_tpu.ops import pallas_life
    from mpi_and_open_mp_tpu.serve import aotcache
    from mpi_and_open_mp_tpu.utils.timing import anchor_sync

    shape = tuple(int(x) for x in shape)
    b, ny, nx = shape
    spec = stencils.get(workload)
    stack = _build_stack(spec, shape)
    stack_j = jnp.asarray(stack)
    cells = b * ny * nx
    on_tpu = jax.default_backend() == "tpu"
    heur = space.heuristic_path(workload, shape, on_tpu)
    cands = space.candidates(workload, shape, on_tpu=on_tpu)
    want = [stencils.oracle_run(spec, stack[i], parity_steps)
            for i in range(b)]

    measurements, rejected = [], []
    for cand in cands:
        with trace.span("tune.candidate", workload=str(workload),
                        path=cand.path, axis_order=cand.axis_order):
            try:
                run = space.runner_for(workload, cand.path)
                got = np.asarray(run(stack_j, jnp.int32(parity_steps)))
                # The parity GATE owns each family's float tolerance
                # (offset keeps the default; sep/fft get their
                # amplification-sized slack from parity_tol_for).
                tol = stencils.parity_tol_for(
                    stencils.family_for_path(cand.path))
                ok = got.shape == stack.shape and all(
                    stencils.parity_ok(spec, got[i], want[i], **tol)
                    for i in range(b))
            except Exception as e:  # noqa: BLE001 — a candidate that
                # cannot dispatch is a rejection, never a crash
                metrics.inc("tune.candidate", status="error")
                rejected.append({
                    "path": cand.path,
                    "reason": f"{type(e).__name__}: {e}"[:200]})
                continue
            if not ok:
                metrics.inc("tune.candidate", status="parity_rejected")
                rejected.append({"path": cand.path, "reason": "parity"})
                continue
            # Warm re-dispatch outside the brackets (n is a runtime
            # scalar: the gate above already compiled this program).
            anchor_sync(run(stack_j, jnp.int32(steps)), fetch_all=True)

            def timed(n):
                best = float("inf")
                for _ in range(max(1, int(reps))):
                    t0 = time.perf_counter()
                    anchor_sync(run(stack_j, jnp.int32(n)),
                                fetch_all=True)
                    best = min(best, time.perf_counter() - t0)
                return best

            t1, t2 = timed(steps), timed(steps * mult)
            differenced = t2 > t1
            steady = ((t2 - t1) / (steps * (mult - 1)) if differenced
                      else t1 / steps)
            metrics.inc("tune.candidate", status="timed")
            measurements.append({
                "path": cand.path,
                "pack_layout": cand.pack_layout,
                "bucket_rounding": cand.bucket_rounding,
                "axis_order": cand.axis_order,
                "steady_s_per_step": steady,
                "cups": round(cells / steady, 1),
                "is_differenced": differenced,
            })
    if not measurements:
        raise RuntimeError(
            f"autotune found no parity-clean candidate for "
            f"{workload} {shape} (rejected: {rejected})")
    best = measurements[0]
    for m in measurements[1:]:
        if m["steady_s_per_step"] < best["steady_s_per_step"]:
            best = m
    heur_meas = next(
        (m for m in measurements if m["path"] == heur), None)
    vs = (round(heur_meas["steady_s_per_step"]
                / best["steady_s_per_step"], 3)
          if heur_meas else None)

    pallas_life.install_planned_path(workload, shape, best["path"])
    result = {
        "workload": str(workload),
        "shape": list(shape),
        "dtype": str(spec.np_dtype),
        "steps_budget": int(steps),
        "heuristic": heur_meas,
        "heuristic_path": heur,
        "tuned": best,
        "vs_heuristic": vs,
        "measurements": measurements,
        "rejected": rejected,
    }
    if store is not None:
        key = plans_mod.fingerprint_for(
            workload, shape, spec.np_dtype, best["path"])
        record = {
            "schema": plans_mod.PLAN_SCHEMA,
            "key": key,
            "choice": {
                "workload": str(workload), "shape": list(shape),
                "dtype": str(spec.np_dtype), "path": best["path"],
                "pack_layout": best["pack_layout"],
                "bucket_rounding": best["bucket_rounding"],
                "axis_order": best["axis_order"],
            },
            "heuristic": heur_meas,
            "tuned": best,
            "vs_heuristic": vs,
            "steps_budget": int(steps),
            "measurements": measurements,
            "rejected": rejected,
        }
        result["plan_file"] = store.save(record)
        result["digest"] = aotcache.digest_for(key)
        if workload == "life":
            # Export the winner's bucket executable into the SAME
            # directory: the plan is installed, so AOTCache computes the
            # IDENTICAL fingerprint -> <digest>.aot beside <digest>.plan.
            _, _, status = aotcache.AOTCache(store.root).ensure(
                shape, spec.np_dtype)
            result["aot_export"] = status
    trace.event("tune.done", workload=str(workload),
                path=best["path"], vs_heuristic=vs or 0.0)
    return result


def tune_sharded(workload: str, shape, *, mesh=None, steps: int = 32,
                 store=None, reps: int = 2, mult: int = 5,
                 parity_steps: int = plans_mod.PARITY_STEPS) -> dict:
    """One bounded SHARDED tuning pass for (workload, board shape):
    profile every legal (axis_order, halo schedule) candidate on a real
    >=2-device mesh — the measured form of PAPERS.md's process-mapping
    axis, which single-device profiling could only enumerate. Same
    discipline as :func:`tune`: oracle parity FIRST, chain-differenced
    brackets, the historic schedule (seq) is always in the race and ties
    keep it. 1-D meshes are legality-gated per layout by
    ``space.sharded_candidates`` (a mesh that shards nothing under a
    layout simply does not list it); a mesh with no legal candidate at
    all raises rather than reporting an empty win."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    from mpi_and_open_mp_tpu import stencils
    from mpi_and_open_mp_tpu.obs import metrics, trace
    from mpi_and_open_mp_tpu.parallel import mesh as mesh_lib
    from mpi_and_open_mp_tpu.serve import aotcache
    from mpi_and_open_mp_tpu.stencils import engine as stencil_engine
    from mpi_and_open_mp_tpu.utils.timing import anchor_sync

    if mesh is None:
        mesh = mesh_lib.make_mesh_2d()
    shape = tuple(int(x) for x in shape)
    ny, nx = shape
    spec = stencils.get(workload)
    board = spec.init(np.random.default_rng(_TUNE_SEED), (ny, nx))
    want = stencils.oracle_run(spec, board, parity_steps)
    cells = ny * nx
    cands = space.sharded_candidates(workload, shape, mesh)
    if not cands:
        raise RuntimeError(
            f"no legal sharded candidate for {workload} {shape} on mesh "
            f"{dict(mesh.shape)} (1-shard axes and non-dividing layouts "
            "are gated out)")
    # Baseline-first: the historic sequential schedule on the first
    # legal layout opens the race, so ties keep it (strict < below).
    cands = sorted(cands, key=lambda c: c.halo_overlap != "seq")

    measurements, rejected = [], []
    for cand in cands:
        layout = cand.axis_order
        ovl = None if cand.halo_overlap == "overlap" else False
        with trace.span("tune.candidate", workload=str(workload),
                        path=cand.path, axis_order=layout,
                        halo_overlap=cand.halo_overlap):
            try:
                if cand.path.startswith("sparse_sharded:"):
                    # Host-driven engine: every timed leg runs a FRESH
                    # engine from the same board (the mask state is the
                    # engine, so reuse would grade a warmer mask).
                    from mpi_and_open_mp_tpu.stencils import (
                        sparse_sharded)

                    def bench_once(n, fuse=cand.fuse_steps):
                        eng = sparse_sharded.SparseShardedEngine(
                            spec, board, mesh=mesh, layout=layout,
                            tile=space.SPARSE_SHARDED_TILE, fuse=fuse)
                        anchor_sync(eng.step(int(n)))
                        return eng

                    parity_eng = bench_once(int(parity_steps))
                    got = parity_eng.snapshot()
                    engine_stamp = parity_eng.engine_stamp
                else:
                    run, plan = stencil_engine.make_sharded_runner(
                        spec, mesh, layout, shape,
                        fuse_steps=cand.fuse_steps,
                        boundary_steps=cand.boundary_steps,
                        overlap=ovl)
                    sharding = NamedSharding(
                        mesh, stencil_engine._sharded_pspec(
                            layout, spec.channels))
                    dev = jax.device_put(
                        jnp.asarray(board, spec.dtype), sharding)

                    def bench_once(n, run=run, dev=dev):
                        anchor_sync(run(dev, int(n)))

                    got = np.asarray(run(dev, int(parity_steps)))
                    engine_stamp = plan.engine
                ok = stencils.parity_ok(spec, got, want)
            except Exception as e:  # noqa: BLE001 — rejection, not crash
                metrics.inc("tune.candidate", status="error")
                rejected.append({
                    "path": cand.path,
                    "halo_overlap": cand.halo_overlap,
                    "reason": f"{type(e).__name__}: {e}"[:200]})
                continue
            if not ok:
                metrics.inc("tune.candidate", status="parity_rejected")
                rejected.append({"path": cand.path,
                                 "halo_overlap": cand.halo_overlap,
                                 "reason": "parity"})
                continue
            bench_once(steps)

            def timed(n):
                best_t = float("inf")
                for _ in range(max(1, int(reps))):
                    t0 = time.perf_counter()
                    bench_once(n)
                    best_t = min(best_t, time.perf_counter() - t0)
                return best_t

            t1, t2 = timed(steps), timed(steps * mult)
            differenced = t2 > t1
            steady = ((t2 - t1) / (steps * (mult - 1)) if differenced
                      else t1 / steps)
            metrics.inc("tune.candidate", status="timed")
            measurements.append({
                "path": cand.path,
                "axis_order": layout,
                "halo_overlap": cand.halo_overlap,
                "fuse_steps": cand.fuse_steps,
                "boundary_steps": cand.boundary_steps,
                "engine": engine_stamp,
                "steady_s_per_step": steady,
                "cups": round(cells / steady, 1),
                "is_differenced": differenced,
            })
    if not measurements:
        raise RuntimeError(
            f"sharded autotune found no parity-clean candidate for "
            f"{workload} {shape} (rejected: {rejected})")
    best = measurements[0]
    for m in measurements[1:]:
        if m["steady_s_per_step"] < best["steady_s_per_step"]:
            best = m
    baseline = measurements[0]  # seq leg, sort above
    vs = round(baseline["steady_s_per_step"]
               / best["steady_s_per_step"], 3)
    # The coupled-depth heuristic — overlap at fuse depth 1, boundary
    # depth coupled (what the pre-depth-axis tuner always picked) — is
    # in every race where overlap is legal, so vs_heuristic >= 1.0 by
    # construction; where the geometry gates overlap out entirely, the
    # sequential baseline IS the heuristic.
    heur = next((m for m in measurements
                 if m["halo_overlap"] == "overlap"
                 and m["fuse_steps"] == 1), baseline)
    vs_heur = round(heur["steady_s_per_step"]
                    / best["steady_s_per_step"], 3)

    py, px = (mesh.shape.get("y", 1), mesh.shape.get("x", 1))
    result = {
        "workload": str(workload),
        "shape": list(shape),
        "dtype": str(spec.np_dtype),
        "mesh_axes": [py, px],
        "steps_budget": int(steps),
        "baseline": baseline,
        "heuristic": heur,
        "tuned": best,
        "vs_sequential": vs,
        "vs_heuristic": vs_heur,
        "measurements": measurements,
        "rejected": rejected,
    }
    if store is not None:
        key = plans_mod.fingerprint_for(
            workload, shape, spec.np_dtype, best["path"])
        record = {
            "schema": plans_mod.PLAN_SCHEMA,
            "key": key,
            "choice": {
                "workload": str(workload), "shape": list(shape),
                "dtype": str(spec.np_dtype), "path": best["path"],
                "pack_layout": "-",
                "bucket_rounding": space.BUCKET_POW2,
                "axis_order": best["axis_order"],
                "halo_overlap": best["halo_overlap"],
                "fuse_steps": best["fuse_steps"],
                "boundary_steps": best["boundary_steps"],
                "mesh_axes": [py, px],
                # Sparse winners re-run through a fresh engine at
                # install parity; the tile rides along so the rebuild
                # is exactly the profiled geometry.
                **({"tile": space.SPARSE_SHARDED_TILE}
                   if best["path"].startswith("sparse_sharded:") else {}),
            },
            "heuristic": heur,
            "tuned": best,
            "vs_heuristic": vs_heur,
            "vs_sequential": vs,
            "steps_budget": int(steps),
            "measurements": measurements,
            "rejected": rejected,
        }
        result["plan_file"] = store.save(record)
        result["digest"] = aotcache.digest_for(key)
    trace.event("tune.sharded.done", workload=str(workload),
                path=best["path"], axis_order=best["axis_order"],
                halo_overlap=best["halo_overlap"],
                fuse_steps=best["fuse_steps"],
                boundary_steps=best["boundary_steps"],
                vs_sequential=vs, vs_heuristic=vs_heur)
    return result
