"""Durable tuned-plan store: one fingerprint, plan AND executable.

A tuned plan is only worth persisting if the process that reloads it can
PROVE it still applies. Three gates run before a record may steer a
dispatch, mirrored exactly from ``serve/aotcache.py``:

1. **Envelope** — ``momp-plan/1`` records are CRC-framed like AOT
   artifacts (magic + length + CRC32 + pickle). A flipped bit anywhere
   is ``corrupt``; the file is quarantined via
   ``utils.checkpoint.quarantine`` and the heuristics serve unchanged.
2. **Fingerprint** — the record's key is the SAME dict
   ``serve.aotcache.fingerprint`` computes, evaluated with the plan's
   choice pinned in (:func:`fingerprint_for`). Any drift — jax/jaxlib
   version, kernel source hash, platform, silicon, topology — recomputes
   to a different key and the record is ``stale``. Because the digest is
   shared, ``<digest>.plan`` sits next to the ``<digest>.aot`` the serve
   layer builds once the plan is installed: one identity for the
   decision and its compiled form.
3. **Parity** — before installation the plan's engine must reproduce the
   NumPy oracle on a seeded stack. For life plans with a co-located
   ``.aot`` the gate runs the stored ``jax.export`` executable itself
   (``Exported.call`` — zero retraces, the same binary that will serve);
   otherwise the live engine. A wrong answer quarantines the plan with
   label ``parity`` — it is never installed, whatever it claims to win.

``MOMP_TUNE=0`` short-circuits :meth:`PlanStore.install` entirely — the
kill switch restores pure-heuristic behavior without touching the store.
"""

from __future__ import annotations

import glob
import os
import pickle
import struct
import zlib

import numpy as np

from mpi_and_open_mp_tpu.serve import aotcache
from mpi_and_open_mp_tpu.utils import checkpoint as checkpoint_mod

PLAN_MAGIC = b"MOMP-PLAN/1\n"
PLAN_SCHEMA = "momp-plan/1"
_HEADER = struct.Struct(">QI")  # payload length, CRC32

#: Oracle steps for the install-time parity gate — enough for a wrong
#: engine/rule/layout to diverge, cheap enough to run on every install.
PARITY_STEPS = 8
_PARITY_SEED = 46


class PlanError(ValueError):
    """A plan record that must not steer a dispatch. ``kind`` is the
    provenance bucket: ``"corrupt"`` (bad magic/length/CRC/undecodable
    payload/malformed record) or ``"stale"`` (intact envelope written
    under a different schema or environment)."""

    def __init__(self, kind: str, msg: str):
        super().__init__(msg)
        self.kind = kind


def fingerprint_for(workload: str, shape, dtype, path: str) -> dict:
    """The aotcache fingerprint WITH the plan's choice pinned in — the
    trick that co-locates plan and executable: the serving process
    computes this exact dict once the plan is installed (its
    ``engine_path`` field reflects the planned path), so both sides
    agree on one digest. Non-life fingerprints pin the life entry OUT
    instead, so they never depend on which life plan happens to be
    installed when they are computed."""
    from mpi_and_open_mp_tpu.ops import pallas_life

    shape = tuple(int(x) for x in shape)
    if len(shape) == 2:
        # Board-shape (sharded-schedule) plans fingerprint as a
        # stack-of-one: same digest machinery, and the pinned
        # "sharded:*" path keeps them disjoint from batched plans.
        shape = (1, *shape)
    pin = str(path) if workload == "life" else None
    with pallas_life._planned_pinned("life", shape, pin):
        return aotcache.fingerprint(shape, dtype, workload=str(workload))


def save_plan(path: str, record: dict) -> None:
    """Write one plan record crash-atomically (the same CRC frame +
    tmp/fsync/replace/dir-fsync dance as ``aotcache.save_artifact``)."""
    payload = pickle.dumps(record, protocol=pickle.HIGHEST_PROTOCOL)
    framed = (PLAN_MAGIC
              + _HEADER.pack(len(payload), zlib.crc32(payload))
              + payload)
    tmp = path + ".tmp"
    with open(tmp, "wb") as fd:
        fd.write(framed)
        fd.flush()
        os.fsync(fd.fileno())
    os.replace(tmp, path)
    checkpoint_mod._fsync_dir(path)


def load_plan(path: str) -> dict:
    """Read one record back, fully validated BEFORE it can steer
    anything: magic, header, length, CRC, payload decode (failures are
    ``corrupt``), then the schema stamp (``stale``). Returns the record
    dict; raises :class:`PlanError`."""
    try:
        with open(path, "rb") as fd:
            framed = fd.read()
    except OSError as e:
        raise PlanError(
            "corrupt", f"unreadable plan record at {path} "
            f"({type(e).__name__}: {e})") from e
    head = len(PLAN_MAGIC) + _HEADER.size
    if not framed.startswith(PLAN_MAGIC):
        raise PlanError(
            "corrupt", f"plan record at {path} has a bad magic header — "
            "not a MOMP-PLAN/1 file (or corrupted at offset 0)")
    if len(framed) < head:
        raise PlanError(
            "corrupt", f"plan record at {path} is truncated inside its "
            f"header ({len(framed)} of {head} header bytes)")
    length, want_crc = _HEADER.unpack(framed[len(PLAN_MAGIC):head])
    payload = framed[head:]
    if len(payload) != length:
        raise PlanError(
            "corrupt", f"plan record at {path} is truncated: payload is "
            f"{len(payload)} bytes, header promises {length}")
    if zlib.crc32(payload) != want_crc:
        raise PlanError(
            "corrupt", f"plan record at {path} failed its CRC "
            f"(stored {want_crc:#010x}, recomputed "
            f"{zlib.crc32(payload):#010x}) — the file is corrupt")
    try:
        record = pickle.loads(payload)
    except Exception as e:  # noqa: BLE001 — any decode failure
        raise PlanError(
            "corrupt", f"plan record at {path} passed its CRC but failed "
            f"to decode ({type(e).__name__}: {e})"[:400]) from e
    if not isinstance(record, dict) or record.get("schema") != PLAN_SCHEMA:
        raise PlanError(
            "stale", f"plan record at {path} carries schema "
            f"{record.get('schema') if isinstance(record, dict) else '?'!r},"
            f" want {PLAN_SCHEMA!r}")
    if not isinstance(record.get("key"), dict) \
            or not isinstance(record.get("choice"), dict):
        raise PlanError(
            "corrupt", f"plan record at {path} decodes but is missing its "
            "key/choice fields")
    return record


class PlanStore:
    """One directory of ``<digest>.plan`` records (plus the serve
    layer's ``<digest>.aot`` executables living beside them).

    ``install()`` is the one entry point: scan, validate, parity-gate,
    then hand every surviving choice to
    ``pallas_life.install_planned_path`` so ``native_path_batch``
    consults it before the heuristics. Every rejection is quarantined
    on disk, counted, and traced — plan rot is observable, never
    silent, and the behavioral fallback is always "the heuristics,
    unchanged"."""

    def __init__(self, root: str | os.PathLike):
        self.root = os.path.abspath(os.fspath(root))
        os.makedirs(self.root, exist_ok=True)
        self._installed: dict[tuple, dict] = {}

    def plan_path(self, digest: str) -> str:
        return os.path.join(self.root, digest + ".plan")

    def save(self, record: dict) -> str:
        """Persist one tuned record under its fingerprint digest;
        returns the file path."""
        path = self.plan_path(aotcache.digest_for(record["key"]))
        save_plan(path, record)
        return path

    def lookup(self, workload: str, shape) -> dict | None:
        """The INSTALLED record for (workload, stack shape), or None."""
        from mpi_and_open_mp_tpu.ops import pallas_life

        return self._installed.get(pallas_life._plan_key(workload, shape))

    def _note(self, status: str, **fields) -> None:
        from mpi_and_open_mp_tpu.obs import metrics, trace

        metrics.inc("tune.plan", status=status)
        trace.event("tune.plan", status=status, **fields)

    def install(self, parity_gate: bool = True) -> dict:
        """Scan the store, validate and parity-gate every record, and
        install the survivors. Returns the bookkeeping summary the
        daemon/bench lines stamp."""
        from mpi_and_open_mp_tpu.ops import pallas_life

        summary = {"scanned": 0, "installed": 0, "corrupt": 0,
                   "stale": 0, "parity_rejected": 0, "disabled": False,
                   "plans": []}
        if not pallas_life._tune_enabled():
            summary["disabled"] = True
            return summary
        for path in sorted(glob.glob(os.path.join(self.root, "*.plan"))):
            summary["scanned"] += 1
            try:
                record = load_plan(path)
                choice = record["choice"]
                workload = str(choice["workload"])
                shape = tuple(int(x) for x in choice["shape"])
                dtype, engine = choice["dtype"], str(choice["path"])
            except PlanError as e:
                summary[e.kind] += 1
                q = checkpoint_mod.quarantine(path, label=e.kind)
                self._note(e.kind, path=path, quarantined=q or "",
                           error=str(e)[:200])
                continue
            except Exception as e:  # noqa: BLE001 — malformed choice
                summary["corrupt"] += 1
                q = checkpoint_mod.quarantine(path, label="corrupt")
                self._note("corrupt", path=path, quarantined=q or "",
                           error=f"{type(e).__name__}: {e}"[:200])
                continue
            want = fingerprint_for(workload, shape, dtype, engine)
            if record["key"] != want:
                drift = sorted(k for k in set(record["key"]) | set(want)
                               if record["key"].get(k) != want.get(k))
                summary["stale"] += 1
                q = checkpoint_mod.quarantine(path, label="stale")
                self._note("stale", path=path, quarantined=q or "",
                           error=f"fingerprint drift: {drift}"[:200])
                continue
            if engine.startswith(("sharded:", "sparse_sharded:")):
                # Sharded-schedule records (tune.runner.tune_sharded):
                # no batched engine to pin — the choice is an
                # (axis_order, halo schedule) pair the sharded runner
                # consults via lookup_sharded(). Parity-gated through
                # the sharded runner itself on the record's own mesh
                # (sparse_sharded winners rebuild a fresh engine at the
                # persisted tile + fuse depth instead).
                if parity_gate and not self._sharded_parity_ok(
                        record, path):
                    summary["parity_rejected"] += 1
                    continue
                self._installed[("sharded", workload, shape)] = record
            else:
                if parity_gate and not self._parity_ok(record, path):
                    summary["parity_rejected"] += 1
                    continue
                pallas_life.install_planned_path(workload, shape, engine)
                self._installed[
                    pallas_life._plan_key(workload, shape)] = record
            summary["installed"] += 1
            summary["plans"].append({
                "workload": workload, "shape": list(shape),
                "path": engine,
                "vs_heuristic": record.get("vs_heuristic")})
            self._note("installed", path=path, workload=workload,
                       engine=engine)
        return summary

    def lookup_sharded(self, workload: str, shape) -> dict | None:
        """The INSTALLED sharded-schedule record for (workload, BOARD
        shape), or None."""
        return self._installed.get(
            ("sharded", str(workload), tuple(int(x) for x in shape)))

    def _sharded_parity_ok(self, record: dict, plan_file: str) -> bool:
        """Parity gate for a sharded-schedule record: rebuild the
        choice's mesh and drive the sharded runner against the oracle.
        The fingerprint gate already pinned the topology, so the mesh is
        reconstructible here; any failure rejects the plan and the
        un-tuned schedule serves unchanged."""
        from mpi_and_open_mp_tpu import stencils
        from mpi_and_open_mp_tpu.parallel import mesh as mesh_lib
        from mpi_and_open_mp_tpu.stencils import engine as stencil_engine

        choice = record["choice"]
        try:
            workload = str(choice["workload"])
            ny, nx = (int(x) for x in choice["shape"])
            py, px = (int(x) for x in choice["mesh_axes"])
            spec = stencils.get(workload)
            mesh = mesh_lib.make_mesh_2d(py, px)
            board = spec.init(np.random.default_rng(_PARITY_SEED),
                              (ny, nx))
            fuse = int(choice.get("fuse_steps", 1))
            if str(choice["path"]).startswith("sparse_sharded:"):
                from mpi_and_open_mp_tpu.stencils import sparse_sharded

                eng = sparse_sharded.SparseShardedEngine(
                    spec, board, mesh=mesh,
                    layout=str(choice["axis_order"]),
                    tile=int(choice["tile"]), fuse=fuse)
                eng.step(PARITY_STEPS)
                out = eng.snapshot()
            else:
                out = stencil_engine.run_sharded(
                    spec, board, PARITY_STEPS, mesh=mesh,
                    layout=str(choice["axis_order"]),
                    fuse_steps=fuse,
                    boundary_steps=int(choice.get("boundary_steps",
                                                  fuse)),
                    overlap=(None
                             if choice.get("halo_overlap") == "overlap"
                             else False))
            ok = stencils.parity_ok(
                spec, np.asarray(out),
                stencils.oracle_run(spec, board, PARITY_STEPS))
        except Exception as e:  # noqa: BLE001 — rejection, never a crash
            ok = False
            self._note("parity_error", path=plan_file,
                       error=f"{type(e).__name__}: {e}"[:200])
        if not ok:
            q = checkpoint_mod.quarantine(plan_file, label="parity")
            self._note("parity_rejected", path=plan_file,
                       quarantined=q or "")
        return ok

    def _parity_ok(self, record: dict, plan_file: str) -> bool:
        """Prove the plan's engine against the NumPy oracle before it
        may steer anything. Life plans with a co-located ``.aot`` gate
        the stored executable itself — the exact binary a warm serve
        process dispatches, so a wrong/foreign artifact rejects the
        plan; an UNREADABLE artifact merely quarantines itself (the
        serve layer rebuilds it) and the gate falls back to the live
        engine. A parity failure quarantines the plan as ``parity``."""
        import jax.numpy as jnp

        from mpi_and_open_mp_tpu import stencils
        from mpi_and_open_mp_tpu.ops import pallas_life
        from mpi_and_open_mp_tpu.tune import space

        choice = record["choice"]
        workload = str(choice["workload"])
        shape = tuple(int(x) for x in choice["shape"])
        b, ny, nx = shape
        try:
            spec = stencils.get(workload)
            rng = np.random.default_rng(_PARITY_SEED)
            stack = np.stack(
                [spec.init(rng, (ny, nx)) for _ in range(b)]
            ).astype(np.dtype(choice["dtype"]))
            aot = os.path.join(
                self.root, aotcache.digest_for(record["key"]) + ".aot")
            exp = None
            if workload == "life" and os.path.exists(aot):
                try:
                    exp = aotcache.load_artifact(aot, record["key"])
                except aotcache.ArtifactError as e:
                    checkpoint_mod.quarantine(aot, label=e.kind)
                    self._note("aot_" + e.kind, path=aot,
                               error=str(e)[:200])
            if exp is not None:
                got = np.asarray(exp.call(jnp.asarray(stack),
                                          jnp.int32(PARITY_STEPS)))
            else:
                with pallas_life._planned_pinned(
                        workload, shape, str(choice["path"])):
                    run = space.runner_for(workload, str(choice["path"]))
                    got = np.asarray(run(jnp.asarray(stack),
                                         jnp.int32(PARITY_STEPS)))
            # The gate owns each engine family's float tolerance —
            # stencil:sep / stencil:fft records gate at their family's
            # slack, everything else at the default.
            tol = stencils.parity_tol_for(
                stencils.family_for_path(str(choice["path"])))
            ok = got.shape == stack.shape and all(
                stencils.parity_ok(
                    spec, got[i],
                    stencils.oracle_run(spec, stack[i], PARITY_STEPS),
                    **tol)
                for i in range(b))
        except Exception as e:  # noqa: BLE001 — a broken engine is a
            # rejection, never a crash: the heuristics keep serving.
            ok = False
            self._note("parity_error", path=plan_file,
                       error=f"{type(e).__name__}: {e}"[:200])
        if not ok:
            q = checkpoint_mod.quarantine(plan_file, label="parity")
            self._note("parity_rejected", path=plan_file,
                       quarantined=q or "")
        return ok
