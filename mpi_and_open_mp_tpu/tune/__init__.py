"""Unified autotuner + durable plan store (DESIGN.md §16).

``tune.space`` enumerates the LEGAL candidates for one (workload, stack
shape, dtype, topology) — engine path, pack layout, batch-bucket
rounding, decomposition axis order. ``tune.runner`` measures them with
the repo's chained-dispatch differencing, parity-gating every timed
candidate against the NumPy oracle first. ``tune.plans`` persists the
winner as a CRC-framed ``momp-plan/1`` record under the SAME fingerprint
digest ``serve/aotcache.py`` computes, so one store directory holds the
decision (``<digest>.plan``) and its compiled form (``<digest>.aot``)
side by side, with the same corrupt/stale quarantine-and-rebuild
semantics.

Runtime knobs: ``MOMP_TUNE_PLANS`` points daemons/bench at a store
directory; ``MOMP_TUNE=0`` is the kill switch (heuristics only, plans
ignored untouched).
"""

from .plans import (  # noqa: F401
    PLAN_MAGIC,
    PLAN_SCHEMA,
    PlanError,
    PlanStore,
    fingerprint_for,
    load_plan,
    save_plan,
)
from .runner import tune, tune_sharded  # noqa: F401
from .space import (  # noqa: F401
    Candidate,
    axis_orders,
    candidates,
    heuristic_path,
    runner_for,
    sharded_candidates,
)
