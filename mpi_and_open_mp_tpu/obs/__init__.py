"""Observability: span tracing, a metrics registry, and trace reporting.

The reference repo's entire observability story is one ``MPI_Wtime``
bracket printed from rank 0 plus a hand-grown ``times.txt``
(``/root/reference/3-life/life_mpi.c:50,64-67``). This package is its
TPU-native replacement, zero-dependency (stdlib only) and zero-overhead
when off:

``trace``
    Nestable spans with a context-manager API, monotonic durations
    (``utils.timing.Timer`` is the clock), process/host ids, and a JSONL
    sink selected by ``MOMP_TRACE=path``. Spans close through
    ``anchor_sync`` so async device work is attributed to the span that
    dispatched it. When ``MOMP_TRACE`` is unset every call degenerates to
    one env lookup returning a shared no-op span — the chaos layer's
    ``is None`` discipline.
``metrics``
    Process-wide counters/gauges/histograms: jit retraces per function,
    ring hops per engine, traced halo exchanges, guard validations and
    ``:recovered`` ladder falls, checkpoint bytes/durations. On by
    default (host-side dict ops); ``MOMP_METRICS=0`` no-ops every
    recorder. ``bench.py`` publishes ``snapshot()`` on its JSON line.
``telemetry``
    The fleet time-series layer over the registry: bounded per-worker
    snapshot rings (periodic deltas, paired mono/wall clock stamps),
    fixed-bucket latency histograms with p50/p99/p999 readout and a
    DECLARED bucket error, the multi-window SLO burn-rate monitor the
    elasticity controller's decisions record, and the length-prefixed
    CRC-framed sidecar stream worker subprocesses ship snapshots over
    (a kill -9 loses at most one partial frame, and the loss is
    counted). ``MOMP_TELEMETRY=0`` switches the plane off.
``report``
    Pure-host analysis of a trace file: per-phase breakdown, α+βn fit
    over ring-hop transfer spans, recovery/retrace summary, and a Chrome
    trace-event exporter (``to_chrome``) so span timelines open in
    Perfetto. CLI form: ``analysis/trace_report.py`` (``--chrome``).
``ledger``
    The CROSS-run layer: an append-only JSONL run ledger where every
    bench line lands stamped with git SHA, platform/device kind, and a
    (topology, shape, dtype, batch, engine) configuration key — the
    baseline store ``analysis/regression_sentinel.py`` judges new runs
    against. Stdlib-only; safe on chip-forbidden hosts.
``profile``
    Compiled-artifact introspection: ``cost_analysis()`` FLOPs/bytes per
    phase, roofline placement against per-device-kind peaks, compile-time
    histograms and live-buffer/memory gauges through the metrics
    registry, and cost-cache hit/miss counters extending the retrace
    accounting.
"""

from mpi_and_open_mp_tpu.obs import (  # noqa: F401
    ledger, metrics, telemetry, trace)
