"""Compiled-artifact introspection: cost models, rooflines, memory gauges.

The bench discipline so far records *rates* (cups, TFLOP/s) against the
reference baseline; nothing says how far a rate sits from what the silicon
could do. This module closes that: lower-and-compile a phase's function
once (outside every timing bracket), read XLA's own
``compiled.cost_analysis()`` FLOPs/bytes, and turn a measured
seconds-per-step into a roofline fraction against the device's peak
compute and memory bandwidth — the annotation every cups number on the
bench line now carries.

Three instruments, all feeding the PR 4 metrics registry:

* :func:`cost` — lower+compile on abstract shapes, return
  ``{"flops", "bytes", "compile_seconds", ...memory sizes}``. Memoised per
  (name, arg shapes/dtypes); the ``profile.cost_cache{result=hit|miss}``
  counters extend the ``jit.retrace`` accounting to the profiling layer,
  and compile wall-time lands in the ``profile.compile_seconds{fn=...}``
  histogram.
* :func:`roofline` — achieved FLOP/s and bytes/s vs per-device-kind peaks
  (:data:`_PEAKS`; override with ``MOMP_PEAK_FLOPS`` /
  ``MOMP_PEAK_BYTES_S`` when the table's entry is wrong for your part).
  CPU peaks are NOMINAL order-of-magnitude host numbers — they keep the
  fraction finite and comparable run-over-run on fallback lines, they do
  not claim to model the host.
* :func:`record_memory_gauges` — live-buffer bytes (``jax.live_arrays``),
  a process-lifetime watermark, and per-device ``memory_stats`` bytes in
  use where the backend exposes them, as registry gauges so they ride the
  bench line's ``metrics`` sub-object.

Cost numbers are MODELS of the work (XLA's static analysis of one
compiled step — a Pallas custom call contributes its operands, not its
internal FLOPs), so ``bench.py`` stamps which function the cost came from
(``roofline.model``); the measured seconds are real either way.
"""

from __future__ import annotations

import math
import os
import time

from mpi_and_open_mp_tpu.obs import metrics

#: (device_kind substring, peak FLOP/s, peak bytes/s). Matched
#: case-insensitively in order; first hit wins. TPU rows are bf16 peak +
#: HBM bandwidth from the public chip specs; the CPU row is a NOMINAL
#: host-class placeholder (see module docs).
_PEAKS: tuple[tuple[str, float, float], ...] = (
    ("v5 lite", 197e12, 819e9),  # v5e ("TPU v5 lite" is the kind string)
    ("v5e", 197e12, 819e9),
    ("v5p", 459e12, 2765e9),
    ("v6", 918e12, 1640e9),
    ("v4", 275e12, 1228e9),
    ("v3", 123e12, 900e9),
    ("v2", 45e12, 700e9),
    ("cpu", 1e11, 2e10),
)
_DEFAULT_PEAKS = ("cpu-nominal", 1e11, 2e10)

_COST_CACHE: dict[tuple, dict] = {}


def peaks_for(device_kind: str | None) -> tuple[float, float, str]:
    """``(peak_flops_per_sec, peak_bytes_per_sec, label)`` for a device
    kind, env-overridable per component."""
    label, flops, bw = _DEFAULT_PEAKS
    kind = (device_kind or "").lower()
    for sub, f, b in _PEAKS:
        if sub in kind:
            label, flops, bw = f"{sub}-table", f, b
            break
    try:
        flops = float(os.environ.get("MOMP_PEAK_FLOPS", flops))
        bw = float(os.environ.get("MOMP_PEAK_BYTES_S", bw))
    except ValueError:
        pass
    return flops, bw, label


def _first_dict(cost_analysis) -> dict:
    # jax 0.4.x returns list[dict]; newer returns the dict itself.
    if isinstance(cost_analysis, (list, tuple)):
        return cost_analysis[0] if cost_analysis else {}
    return cost_analysis or {}


def cost(fn, *args, static_argnums=(), name: str | None = None) -> dict:
    """FLOPs/bytes/compile-time of ``fn`` compiled for ``args``' shapes.

    ``args`` may be ``jax.ShapeDtypeStruct``s — nothing executes; the
    artifact is lowered, compiled, and introspected. Raises whatever the
    lowering raises: callers decide whether a missing cost model costs a
    field or the run.
    """
    import jax

    name = name or getattr(fn, "__name__", "fn")
    sig = (name, tuple(
        (tuple(a.shape), str(a.dtype)) if hasattr(a, "shape") else repr(a)
        for a in args), tuple(static_argnums))
    cached = _COST_CACHE.get(sig)
    if cached is not None:
        metrics.inc("profile.cost_cache", result="hit")
        return dict(cached)
    metrics.inc("profile.cost_cache", result="miss")
    t0 = time.perf_counter()
    compiled = jax.jit(fn, static_argnums=static_argnums).lower(
        *args).compile()
    compile_seconds = time.perf_counter() - t0
    ca = _first_dict(compiled.cost_analysis())
    out = {
        "flops": float(ca.get("flops", float("nan"))),
        "bytes": float(ca.get("bytes accessed", float("nan"))),
        "compile_seconds": round(compile_seconds, 6),
    }
    try:
        ma = compiled.memory_analysis()
        out.update({
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
            "code_bytes": int(ma.generated_code_size_in_bytes),
        })
    except Exception:  # noqa: BLE001 — memory stats are backend-optional
        pass
    metrics.observe("profile.compile_seconds", compile_seconds, fn=name)
    if "temp_bytes" in out:
        metrics.gauge("profile.temp_bytes", out["temp_bytes"], fn=name)
    _COST_CACHE[sig] = dict(out)
    return out


def roofline(flops_per_step: float, bytes_per_step: float,
             seconds_per_step: float,
             device_kind: str | None = None) -> dict:
    """Roofline placement of a measured per-step time against a cost
    model: achieved rates, peak fractions, and which ceiling binds."""
    peak_flops, peak_bw, label = peaks_for(device_kind)
    if not (seconds_per_step > 0 and math.isfinite(seconds_per_step)):
        raise ValueError(
            f"seconds_per_step must be finite/positive: {seconds_per_step}")
    flops_rate = flops_per_step / seconds_per_step
    bytes_rate = bytes_per_step / seconds_per_step
    flops_frac = flops_rate / peak_flops
    bw_frac = bytes_rate / peak_bw
    return {
        "flops_per_step": flops_per_step,
        "bytes_per_step": bytes_per_step,
        "flops_per_sec": round(flops_rate, 1),
        "bytes_per_sec": round(bytes_rate, 1),
        "flops_pct": round(100 * flops_frac, 3),
        "bw_pct": round(100 * bw_frac, 3),
        # The binding ceiling — the larger fraction is the wall the
        # measured rate actually sits under.
        "bound": "memory" if bw_frac >= flops_frac else "compute",
        "roofline_pct": round(100 * max(flops_frac, bw_frac), 3),
        "peaks": label,
        "peak_flops_per_sec": peak_flops,
        "peak_bytes_per_sec": peak_bw,
    }


_WATERMARK = 0


def live_buffer_bytes() -> int:
    """Total bytes of live device arrays in this process."""
    import jax

    return sum(int(getattr(a, "nbytes", 0)) for a in jax.live_arrays())


def record_memory_gauges() -> int:
    """Gauge live-buffer bytes + process watermark (+ per-device
    ``memory_stats`` where the backend exposes them); returns the live
    total."""
    import jax

    global _WATERMARK
    live = live_buffer_bytes()
    _WATERMARK = max(_WATERMARK, live)
    metrics.gauge("memory.live_buffer_bytes", live)
    metrics.gauge("memory.live_buffer_watermark_bytes", _WATERMARK)
    for dev in jax.local_devices():
        try:
            stats = dev.memory_stats()
        except Exception:  # noqa: BLE001 — CPU backends have none
            stats = None
        if stats and "bytes_in_use" in stats:
            metrics.gauge("memory.device_bytes_in_use",
                          stats["bytes_in_use"], device=str(dev.id))
    return live


def reset_cost_cache() -> None:
    """Empty the cost memo (tests)."""
    _COST_CACHE.clear()
