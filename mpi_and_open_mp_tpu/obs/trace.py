"""Span tracer: nested wall-clock spans + instant events to a JSONL sink.

Activation mirrors ``robust.chaos``: the ``MOMP_TRACE`` environment
variable selects the sink path; when unset, :func:`span` returns a shared
no-op singleton and :func:`event` returns immediately — one env lookup,
no allocation, no I/O, nothing reachable. The sink is cached per env
value (like ``chaos.active_plan``'s ``_CACHE``) and opened in APPEND
mode, so multiple processes/invocations may share one trace file (the CI
trace cycle relies on this).

Record schema, one JSON object per line::

    {"kind": "span",  "name": ..., "ts": <epoch sec>, "dur": <sec>,
     "id": N, "parent": M|null, "pid": ..., "host": ..., "attrs": {...}}
    {"kind": "event", "name": ..., "ts": <epoch sec>,
     "id": N, "parent": M|null, "pid": ..., "host": ..., "attrs": {...}}

Spans are written at EXIT (children before parents — reconstruct nesting
via ``parent``). The duration clock is ``utils.timing.Timer`` — the one
wall-clock implementation in the framework.

Device-work attribution: JAX dispatch is async, so a span that merely
brackets a dispatch times the enqueue, not the work. Call
``span.anchor(tree)`` with the dispatched output; the span then closes
through ``anchor_sync(tree, fetch_all=True)`` (block + one-element shard
fetch — ``block_until_ready`` alone returns early on tunneled-TPU mesh
arrays) so ``dur`` covers the device work the span claims to measure.
"""

from __future__ import annotations

import itertools
import json
import os
import socket
import threading

from mpi_and_open_mp_tpu.utils.timing import Timer, anchor_sync

_ENV = "MOMP_TRACE"
_ENV_HOPS = "MOMP_TRACE_HOPS"

_CACHE: tuple[str | None, object | None] = (None, None)
_IDS = itertools.count(1)
_LOCAL = threading.local()
_HOST: str | None = None
_WRITE_LOCK = threading.Lock()


def enabled() -> bool:
    """Whether tracing is on (``MOMP_TRACE`` names a sink path)."""
    return bool(os.environ.get(_ENV, ""))


def hop_spans_active() -> bool:
    """Whether per-hop ring instrumentation should engage: tracing on and
    not opted out via ``MOMP_TRACE_HOPS=0`` (the hop-by-hop traced ring
    dispatch re-plans the forward as p-1 host-anchored hops — always
    parity-exact, but a different dispatch shape than the fused
    ``fori_loop`` ring; the opt-out keeps whole-call spans only)."""
    return enabled() and os.environ.get(_ENV_HOPS, "1") != "0"


def _sink():
    """The open line-buffered sink for the current ``MOMP_TRACE`` value,
    or ``None``. Cached per value; a changed path closes the old file."""
    global _CACHE
    raw = os.environ.get(_ENV, "")
    if not raw:
        return None
    if _CACHE[0] != raw:
        if _CACHE[1] is not None:
            try:
                _CACHE[1].close()
            except OSError:
                pass
        outdir = os.path.dirname(raw)
        if outdir:
            os.makedirs(outdir, exist_ok=True)
        _CACHE = (raw, open(raw, "a", buffering=1))
    return _CACHE[1]


def reset() -> None:
    """Close and drop the cached sink (tests switch paths mid-process)."""
    global _CACHE
    if _CACHE[1] is not None:
        try:
            _CACHE[1].close()
        except OSError:
            pass
    _CACHE = (None, None)


def _host() -> str:
    global _HOST
    if _HOST is None:
        _HOST = socket.gethostname()
    return _HOST


def _stack() -> list:
    s = getattr(_LOCAL, "stack", None)
    if s is None:
        s = _LOCAL.stack = []
    return s


def _write(rec: dict) -> None:
    fd = _sink()
    if fd is None:  # sink vanished mid-span (env cleared): drop silently
        return
    line = json.dumps(rec, default=str)
    with _WRITE_LOCK:
        fd.write(line + "\n")


class _NullSpan:
    """The off-path span: every method a no-op, one shared instance."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, **attrs) -> "_NullSpan":
        return self

    def anchor(self, tree) -> "_NullSpan":
        return self

    @property
    def elapsed(self) -> float:
        return float("nan")


NULL = _NullSpan()


class Span:
    """One live span. Use via ``with trace.span(name, **attrs) as sp``."""

    __slots__ = ("name", "attrs", "id", "parent", "_timer", "_ts", "_tree")

    def __init__(self, name: str, attrs: dict):
        self.name = name
        self.attrs = attrs
        self._tree = None

    def __enter__(self) -> "Span":
        import time

        stack = _stack()
        self.parent = stack[-1].id if stack else None
        self.id = next(_IDS)
        stack.append(self)
        self._ts = time.time()
        self._timer = Timer().__enter__()
        return self

    def set(self, **attrs) -> "Span":
        """Attach/override attributes mid-span."""
        self.attrs.update(attrs)
        return self

    def anchor(self, tree) -> "Span":
        """Close through ``anchor_sync(tree)``: the span's duration then
        includes the device work behind these (possibly async) arrays."""
        self._tree = tree
        return self

    @property
    def elapsed(self) -> float:
        """Running wall seconds (live inside the ``with`` block)."""
        return self._timer.elapsed

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._tree is not None and exc_type is None:
            anchor_sync(self._tree, fetch_all=True)
            self._tree = None
        self._timer.__exit__()
        stack = _stack()
        if stack and stack[-1] is self:
            stack.pop()
        rec = {
            "kind": "span", "name": self.name, "ts": self._ts,
            "dur": self._timer.elapsed, "id": self.id, "parent": self.parent,
            "pid": os.getpid(), "host": _host(),
        }
        if exc_type is not None:
            rec["error"] = exc_type.__name__
        if self.attrs:
            rec["attrs"] = self.attrs
        _write(rec)
        return False


def span(name: str, **attrs):
    """A new span, or the shared no-op when tracing is off."""
    if not os.environ.get(_ENV, ""):
        return NULL
    return Span(name, attrs)


def event(name: str, **attrs) -> None:
    """An instant (zero-duration) record — recovery stamps, metric
    snapshots. Parented to the innermost open span of this thread."""
    if not os.environ.get(_ENV, ""):
        return
    import time

    stack = _stack()
    rec = {
        "kind": "event", "name": name, "ts": time.time(), "id": next(_IDS),
        "parent": stack[-1].id if stack else None,
        "pid": os.getpid(), "host": _host(),
    }
    if attrs:
        rec["attrs"] = attrs
    _write(rec)
