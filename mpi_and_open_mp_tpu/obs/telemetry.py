"""Fleet telemetry plane: time-series snapshots, quantile histograms,
SLO burn rate, and the cross-process sidecar frame format.

The metrics registry (``obs.metrics``) answers "how many, how long
altogether, worst case" for ONE process at the instant you ask. A fleet
is run on different questions: what is each worker doing NOW, how fast
is the error budget burning, and — after a worker dies — what did its
last interval look like. This module is the layer between the registry
and those questions, stdlib-only and off-path like the rest of ``obs``:

``LatencyHist``
    Fixed geometric buckets with p50/p99/p999 readout — the latency
    series replacement for the registry's min/max-only histograms. The
    bucket ratio is DECLARED (:data:`BUCKET_REL_ERR`): a quantile read
    off the histogram is the bucket's upper edge, so it can overstate
    the exact sample quantile by at most one bucket ratio, and two
    readings agree when their buckets are within one step
    (:meth:`LatencyHist.agrees`). Bucket-count DELTAS are what ships:
    a merged fleet histogram is the sum of shipped deltas, so losing a
    snapshot loses exactly that interval's counts, never the series.
``WorkerTelemetry``
    One worker's recorder: a bounded ring of periodic snapshots (seq,
    monotonic + wall stamps, cumulative counters, histogram delta).
    Bounded means bounded — the ring evicts oldest first and COUNTS the
    evictions, so memory is capped and loss is observable, both.
``BurnRateMonitor``
    Multi-window error-budget consumption over the declared loadgen
    :class:`~mpi_and_open_mp_tpu.serve.loadgen.SLO`. ``burn = bad-frac
    / (1 - goodput_frac)``: burn 1.0 spends the budget exactly at the
    allowed rate; the short window trips fast on a kill, the long
    window filters blips — alerting fires only when BOTH are over
    (the standard multi-window burn-rate alert shape). The windows are
    the recorded, queryable input the elasticity controller's verdicts
    carry (``serve.fleet`` stamps them on every scale/drain decision).
``write_frame`` / ``read_frames``
    The sidecar stream a worker SUBPROCESS ships snapshots over:
    length-prefixed CRC32-framed JSON, append-only. A ``kill -9``
    truncates at worst one partial frame; the reader checks length and
    CRC and soft-lands at the first bad frame, so snapshot loss from a
    death is bounded to the victim's last interval.
``clock_offset``
    Monotonic→wall alignment for the merged timeline: every snapshot
    carries a ``(mono, wall)`` pair sampled together (the heartbeat
    exchange), and the median of ``wall - mono`` is the process's
    offset. Records stamped with monotonic fleet-clock values (the
    scale decisions) map onto the shared wall timeline through it.

Knobs, house convention (default on, ``=0`` disables):
``MOMP_TELEMETRY=0`` turns every recorder into a no-op;
``MOMP_TELEMETRY_INTERVAL`` (seconds, default 0.05) paces snapshots;
``MOMP_TELEMETRY_CAPACITY`` (default 512) bounds each worker ring.
"""

from __future__ import annotations

import collections
import json
import math
import os
import struct
import threading
import time
import zlib

_ENV = "MOMP_TELEMETRY"
_ENV_INTERVAL = "MOMP_TELEMETRY_INTERVAL"
_ENV_CAPACITY = "MOMP_TELEMETRY_CAPACITY"

#: Snapshot schema version (rides every frame; readers reject unknowns).
SNAPSHOT_SCHEMA = 1

#: Latency bucket edges: geometric, 12 per decade from 100 µs to 100 s.
#: Upper edges; an observation lands in the first bucket whose edge is
#: >= the value, values past the last edge land in the overflow bucket.
BUCKET_RATIO = 10.0 ** (1.0 / 12.0)
DEFAULT_BOUNDS = tuple(1e-4 * BUCKET_RATIO ** i for i in range(73))

#: The declared relative quantile error of the default buckets: a
#: histogram quantile is its bucket's upper edge, at most one ratio
#: above the exact sample value in that bucket.
BUCKET_REL_ERR = BUCKET_RATIO - 1.0


def telemetry_on() -> bool:
    """Collection is on unless ``MOMP_TELEMETRY=0``."""
    return os.environ.get(_ENV, "1") != "0"


def snapshot_interval_s() -> float:
    """The configured snapshot cadence (``MOMP_TELEMETRY_INTERVAL``)."""
    try:
        v = float(os.environ.get(_ENV_INTERVAL, "0.05"))
    except ValueError:
        return 0.05
    return v if v > 0 else 0.05


def ring_capacity() -> int:
    """Per-worker snapshot ring size (``MOMP_TELEMETRY_CAPACITY``)."""
    try:
        v = int(os.environ.get(_ENV_CAPACITY, "512"))
    except ValueError:
        return 512
    return v if v > 0 else 512


class LatencyHist:
    """Fixed-bucket latency histogram with quantile readout.

    Buckets are closed on the right: value ``v`` lands in the first
    bucket whose upper edge is >= ``v``; anything past the last edge
    lands in one overflow bucket whose readout is the observed max (the
    honest answer when the tail left the declared range). NaN drops,
    like ``metrics.observe``.
    """

    __slots__ = ("bounds", "counts", "count", "total", "vmin", "vmax")

    def __init__(self, bounds: tuple = DEFAULT_BOUNDS):
        self.bounds = tuple(bounds)
        self.counts = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = 0.0

    def bucket_index(self, v: float) -> int:
        import bisect

        return min(bisect.bisect_left(self.bounds, v), len(self.bounds))

    def observe(self, v: float) -> None:
        v = float(v)
        if math.isnan(v):
            return
        self.counts[self.bucket_index(v)] += 1
        self.count += 1
        self.total += v
        self.vmin = min(self.vmin, v)
        self.vmax = max(self.vmax, v)

    def merge_counts(self, counts, *, total: float = 0.0,
                     vmin: float = math.inf, vmax: float = 0.0) -> None:
        """Fold a shipped bucket-count delta (sparse ``{index: n}`` or
        dense list) into this histogram — how a fleet rollup merges
        worker series without ever seeing the raw samples."""
        items = (counts.items() if isinstance(counts, dict)
                 else enumerate(counts))
        for i, n in items:
            i = int(i)
            n = int(n)
            if 0 <= i < len(self.counts) and n > 0:
                self.counts[i] += n
                self.count += n
        self.total += float(total)
        self.vmin = min(self.vmin, float(vmin))
        self.vmax = max(self.vmax, float(vmax))

    def quantile(self, q: float) -> float:
        """The ``q``-th percentile (``q`` in [0, 100]) as the holding
        bucket's upper edge — within :data:`BUCKET_REL_ERR` of the exact
        nearest-rank sample quantile by construction. 0.0 when empty."""
        if self.count == 0:
            return 0.0
        rank = max(1, min(self.count, int(-(-q * self.count // 100))))
        seen = 0
        for i, n in enumerate(self.counts):
            seen += n
            if seen >= rank:
                if i >= len(self.bounds):
                    return self.vmax
                return self.bounds[i]
        return self.vmax

    def agrees(self, estimate: float, exact: float) -> bool:
        """Whether two latency readings sit within the declared bucket
        error — same or adjacent bucket (quantile readout rounds up,
        nearest-rank rounds to a sample; one bucket step covers both)."""
        return abs(self.bucket_index(estimate)
                   - self.bucket_index(exact)) <= 1

    def snapshot_counts(self) -> list[int]:
        return list(self.counts)

    def to_dict(self) -> dict:
        return {
            "count": self.count,
            "total_s": round(self.total, 6),
            "min_s": round(self.vmin, 6) if self.count else None,
            "max_s": round(self.vmax, 6) if self.count else None,
            "p50_s": round(self.quantile(50), 6),
            "p99_s": round(self.quantile(99), 6),
            "p999_s": round(self.quantile(99.9), 6),
        }


def _sparse_delta(prev: list[int], cur: list[int]) -> dict[str, int]:
    """Bucket-count delta as a sparse ``{str(index): n}`` map (JSON
    object keys are strings; most intervals touch a few buckets)."""
    return {str(i): c - p for i, (p, c) in enumerate(zip(prev, cur))
            if c != p}


class WorkerTelemetry:
    """One worker's bounded time-series recorder.

    ``sample`` is interval-gated (``due``/``force``): each accepted
    sample appends one snapshot — sequence number, paired monotonic +
    wall stamps (the clock-alignment exchange), the caller's cumulative
    counters, and the latency-histogram delta since the previous
    snapshot — to a bounded ring. Eviction increments ``dropped`` so
    the loss a too-small ring causes is itself observable.
    """

    def __init__(self, worker: int, *, interval_s: float | None = None,
                 capacity: int | None = None, bounds: tuple = DEFAULT_BOUNDS):
        self.worker = int(worker)
        self.interval_s = (snapshot_interval_s() if interval_s is None
                           else float(interval_s))
        self.ring: collections.deque = collections.deque(
            maxlen=capacity if capacity is not None else ring_capacity())
        self.hist = LatencyHist(bounds)
        self.dropped = 0
        self.seq = 0
        self._last_mono: float | None = None
        self._last_counts = self.hist.snapshot_counts()

    def observe_latency(self, seconds: float) -> None:
        self.hist.observe(seconds)

    def due(self, now: float) -> bool:
        return (self._last_mono is None
                or now - self._last_mono >= self.interval_s)

    def sample(self, now: float, counters: dict | None = None, *,
               force: bool = False, wall: float | None = None) -> dict | None:
        """Record one snapshot if the interval elapsed (or ``force``).
        Returns the snapshot dict (also kept in the ring) or ``None``."""
        if not force and not self.due(now):
            return None
        cur = self.hist.snapshot_counts()
        snap = {
            "v": SNAPSHOT_SCHEMA,
            "worker": self.worker,
            "seq": self.seq,
            "mono": float(now),
            "wall": time.time() if wall is None else float(wall),
            "counters": dict(counters or {}),
            "hist": _sparse_delta(self._last_counts, cur),
            "hist_count": self.hist.count,
        }
        self.seq += 1
        self._last_mono = now
        self._last_counts = cur
        if len(self.ring) == self.ring.maxlen:
            self.dropped += 1
        self.ring.append(snap)
        return snap

    def series(self) -> list[dict]:
        return list(self.ring)


class BurnRateMonitor:
    """Multi-window SLO error-budget burn over a good/bad event stream.

    ``observe(now, good, bad)`` feeds one interval's counts (bad = shed
    or over-SLO-latency); ``windows(now)`` reads the burn rate over the
    short and long trailing windows. Burn 1.0 = spending the budget
    exactly as fast as the SLO allows; the alert condition is BOTH
    windows over :attr:`alert_burn` — the short window makes a real
    incident (a worker kill) visible within seconds, the long window
    keeps a one-interval blip from paging. Crossing into alert is
    edge-triggered (``alerts`` counts crossings, not intervals).
    """

    def __init__(self, *, slo_p99_s: float = 0.25,
                 goodput_frac: float = 0.9,
                 short_window_s: float = 0.25, long_window_s: float = 1.0,
                 alert_burn: float = 1.0):
        if long_window_s < short_window_s:
            raise ValueError(
                f"long window ({long_window_s}) must be >= short "
                f"({short_window_s})")
        self.slo_p99_s = float(slo_p99_s)
        #: Error budget: the bad-request fraction the SLO tolerates.
        self.budget = max(1.0 - float(goodput_frac), 1e-6)
        self.short_window_s = float(short_window_s)
        self.long_window_s = float(long_window_s)
        self.alert_burn = float(alert_burn)
        self._obs: collections.deque = collections.deque()
        self.peak_short = 0.0
        self.peak_long = 0.0
        self.alerts = 0
        self._alerting = False

    @classmethod
    def from_slo(cls, slo, **kw) -> "BurnRateMonitor":
        """Build over a declared ``serve.loadgen.SLO``."""
        return cls(slo_p99_s=slo.p99_s, goodput_frac=slo.goodput_frac,
                   **kw)

    def is_bad(self, latency_s: float) -> bool:
        return latency_s > self.slo_p99_s

    def _burn(self, now: float, window_s: float) -> float:
        good = bad = 0
        for t, g, b in reversed(self._obs):
            if now - t > window_s:
                break
            good += g
            bad += b
        if good + bad == 0:
            return 0.0
        return (bad / (good + bad)) / self.budget

    def observe(self, now: float, good: int, bad: int) -> dict:
        """Feed one interval; returns the window values, with
        ``alert_edge`` True exactly when this observation crossed into
        the both-windows-burning state."""
        self._obs.append((float(now), int(good), int(bad)))
        while self._obs and now - self._obs[0][0] > self.long_window_s:
            self._obs.popleft()
        win = self.windows(now)
        self.peak_short = max(self.peak_short, win["burn_short"])
        self.peak_long = max(self.peak_long, win["burn_long"])
        alerting = (win["burn_short"] > self.alert_burn
                    and win["burn_long"] > self.alert_burn)
        win["alert_edge"] = alerting and not self._alerting
        if win["alert_edge"]:
            self.alerts += 1
        self._alerting = alerting
        return win

    def windows(self, now: float) -> dict:
        """The queryable burn-rate input: both windows, plus peaks."""
        return {
            "burn_short": round(self._burn(now, self.short_window_s), 4),
            "burn_long": round(self._burn(now, self.long_window_s), 4),
            "short_window_s": self.short_window_s,
            "long_window_s": self.long_window_s,
            "budget": round(self.budget, 6),
        }

    def summary(self) -> dict:
        return {
            "burn_peak_short": round(self.peak_short, 4),
            "burn_peak_long": round(self.peak_long, 4),
            "burn_alerts": self.alerts,
            "budget": round(self.budget, 6),
        }


# -- the cross-process sidecar stream ---------------------------------------
#
# Frame layout, little-endian:  u32 payload length | u32 CRC32(payload)
# | payload (UTF-8 JSON snapshot). Append-only; a reader stops at the
# first frame whose length runs past EOF or whose CRC mismatches — the
# kill -9 truncation contract: at most one partial frame is lost, and
# the loss is COUNTED, not papered over.

_FRAME_HEADER = struct.Struct("<II")
#: Defensive bound: no snapshot is megabytes; a corrupt length field
#: must not allocate the file size.
_MAX_FRAME = 1 << 20


def write_frame(fd, snap: dict) -> int:
    """Append one CRC-framed snapshot; returns bytes written."""
    payload = json.dumps(snap, separators=(",", ":")).encode()
    fd.write(_FRAME_HEADER.pack(len(payload), zlib.crc32(payload)))
    fd.write(payload)
    return _FRAME_HEADER.size + len(payload)


def read_frames(path: str) -> dict:
    """Read every intact frame: ``{"snapshots": [...], "truncated": n,
    "bytes": total}``. ``truncated`` counts the bad tail (0 or 1 for a
    clean kill; >1 only for real corruption) — the reader NEVER raises
    on a short/garbled tail, because a dead worker's stream ending
    mid-frame is the expected shape of the failure being measured."""
    snaps: list[dict] = []
    truncated = 0
    try:
        blob = open(path, "rb").read()
    except OSError:
        return {"snapshots": snaps, "truncated": 0, "bytes": 0}
    off = 0
    n = len(blob)
    while off + _FRAME_HEADER.size <= n:
        length, crc = _FRAME_HEADER.unpack_from(blob, off)
        start = off + _FRAME_HEADER.size
        if length > _MAX_FRAME or start + length > n:
            truncated += 1
            break
        payload = blob[start:start + length]
        if zlib.crc32(payload) != crc:
            truncated += 1
            break
        try:
            snap = json.loads(payload)
        except ValueError:
            truncated += 1
            break
        if isinstance(snap, dict) and snap.get("v") == SNAPSHOT_SCHEMA:
            snaps.append(snap)
        off = start + length
    else:
        if off < n:
            truncated += 1
    return {"snapshots": snaps, "truncated": truncated, "bytes": n}


class SnapshotShipper:
    """Background sidecar writer for a worker subprocess.

    Samples ``sample_fn() -> (counters, new_latencies)`` every interval
    on a daemon thread, observes the latencies into a
    :class:`WorkerTelemetry`, and appends each accepted snapshot as one
    CRC frame. ``stop()`` takes one final forced sample so a CLEAN exit
    ships its last interval; a killed worker simply stops writing — the
    framing bounds that loss to the final interval by construction."""

    def __init__(self, path: str, worker: int, sample_fn, *,
                 interval_s: float | None = None):
        self.path = path
        self.telemetry = WorkerTelemetry(worker, interval_s=interval_s)
        self._sample_fn = sample_fn
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._fd = open(path, "ab", buffering=0)
        self._lock = threading.Lock()

    def _ship(self, *, force: bool = False) -> None:
        now = time.monotonic()
        if not force and not self.telemetry.due(now):
            return
        counters, latencies = self._sample_fn()
        for v in latencies:
            self.telemetry.observe_latency(v)
        snap = self.telemetry.sample(now, counters, force=force)
        if snap is not None:
            with self._lock:
                write_frame(self._fd, snap)

    def _run(self) -> None:
        while not self._stop.wait(self.telemetry.interval_s / 4):
            try:
                self._ship()
            except Exception:  # noqa: BLE001 — telemetry must not kill
                # serving, and a transient race (sampling the queue
                # mid-mutation) must not end the stream: skip the tick.
                continue

    def start(self) -> "SnapshotShipper":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)
        try:
            self._ship(force=True)
        finally:
            self._fd.close()


def clock_offset(snapshots: list[dict]) -> float | None:
    """The process's monotonic→wall offset: median of ``wall - mono``
    over its snapshots (each pair sampled together on the heartbeat, so
    the spread is scheduling jitter, and the median rejects it)."""
    pairs = sorted(s["wall"] - s["mono"] for s in snapshots
                   if isinstance(s.get("wall"), (int, float))
                   and isinstance(s.get("mono"), (int, float)))
    if not pairs:
        return None
    mid = len(pairs) // 2
    if len(pairs) % 2:
        return pairs[mid]
    return 0.5 * (pairs[mid - 1] + pairs[mid])
