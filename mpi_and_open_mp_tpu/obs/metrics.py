"""Process-wide metrics registry: counters, gauges, histograms.

Host-side only — recorders are plain dict updates under a lock, cheap
enough to live on warm paths (a segment boundary, a trace-time function
body, a checkpoint write) but NEVER inside a timed chained-dispatch
bracket. Collection is ON by default; ``MOMP_METRICS=0`` turns every
recorder into an immediate return (the registry stays empty), mirroring
the chaos/trace off-path discipline.

Keys are ``(name, sorted label items)``; :func:`snapshot` renders them
``name{k=v,...}`` — the flat, diffable form ``bench.py`` publishes as
the ``metrics`` sub-object of its JSON line. Histograms keep
count/total/min/max (no buckets: the consumers here want "how many, how
long altogether, worst case", not quantiles — latency SERIES that need
p50/p99/p999 live in ``obs.telemetry.LatencyHist`` on top of this
registry). Label cardinality is capped per metric name
(``MOMP_METRICS_MAX_LABELSETS``, default 256): a high-cardinality label
(per-session ids under loadgen) stops growing the registry at the cap
and ticks ``metrics.dropped_labels`` instead.

What lands here (the instrumented layers):

* ``jit.retrace{fn=...}`` — incremented INSIDE jitted function bodies,
  which only run on a jit-cache miss: the retrace counter per function.
* ``ring.hops.fwd{engine=...}`` / ``ring.steps.traced`` — ring-attention
  hops executed per engine stamp (traced hop-by-hop dispatch).
* ``halo.exchange.traced{kind=...,axis=...}`` — halo exchanges TRACED
  (bodies run at trace time only; executions per step are not
  host-visible from inside a compiled loop — documented, like chaos's
  trace-time injection).
* ``guard.validation{engine=...}`` / ``guard.validation_failed{...}`` /
  ``recovery{stamp=...}`` — the guards ladder (``robust.guards``).
* ``checkpoint.saves`` / ``checkpoint.save.bytes`` /
  ``checkpoint.save_seconds`` (histogram) and the ``restore`` twins.
"""

from __future__ import annotations

import math
import os
import threading

_ENV = "MOMP_METRICS"
_ENV_MAX_LABELSETS = "MOMP_METRICS_MAX_LABELSETS"

#: Overflow counter ticked when the cardinality guard drops a record.
DROPPED_LABELS = "metrics.dropped_labels"

_LOCK = threading.Lock()
_COUNTERS: dict[tuple, float] = {}
_GAUGES: dict[tuple, float] = {}
_HISTS: dict[tuple, list[float]] = {}  # [count, total, min, max]
_LABELSETS: dict[str, int] = {}  # distinct label sets seen per name


def max_labelsets() -> int:
    """Distinct label sets admitted per metric name before the guard
    drops new ones (``MOMP_METRICS_MAX_LABELSETS``, default 256)."""
    try:
        v = int(os.environ.get(_ENV_MAX_LABELSETS, "256"))
    except ValueError:
        return 256
    return v if v > 0 else 256


def _admit(k: tuple, store: dict) -> bool:
    """Cardinality guard, called under ``_LOCK``: an EXISTING key always
    updates; a new key is admitted only while its metric name is under
    the label-set cap. Without this, one per-session label under loadgen
    grows the registry with the traffic — unbounded resident memory and
    a snapshot() that swamps the bench line. Drops tick
    :data:`DROPPED_LABELS` (itself label-free, so never droppable)."""
    if k in store:
        return True
    name = k[0]
    if _LABELSETS.get(name, 0) >= max_labelsets():
        dk = (DROPPED_LABELS, ())
        _COUNTERS[dk] = _COUNTERS.get(dk, 0) + 1
        return False
    _LABELSETS[name] = _LABELSETS.get(name, 0) + 1
    return True


def metrics_on() -> bool:
    """Collection is on unless ``MOMP_METRICS=0``."""
    return os.environ.get(_ENV, "1") != "0"


def _key(name: str, labels: dict) -> tuple:
    # Label values stringify so keys always sort/compare (an int-valued
    # and a str-valued label under one name must not break snapshot()).
    return (name, tuple(sorted((a, str(b)) for a, b in labels.items())))


def inc(name: str, value: float = 1, **labels) -> None:
    """Add to a monotonic counter."""
    if not metrics_on():
        return
    k = _key(name, labels)
    with _LOCK:
        if _admit(k, _COUNTERS):
            _COUNTERS[k] = _COUNTERS.get(k, 0) + value


def gauge(name: str, value: float, **labels) -> None:
    """Set a last-value-wins gauge."""
    if not metrics_on():
        return
    k = _key(name, labels)
    with _LOCK:
        if _admit(k, _GAUGES):
            _GAUGES[k] = value


def observe(name: str, value: float, **labels) -> None:
    """Record one histogram observation (count/total/min/max). NaN
    observations are dropped — a no-op span clock must not poison the
    aggregate."""
    if not metrics_on() or math.isnan(value):
        return
    k = _key(name, labels)
    with _LOCK:
        h = _HISTS.get(k)
        if h is None:
            if not _admit(k, _HISTS):
                return
            _HISTS[k] = [1, value, value, value]
        else:
            h[0] += 1
            h[1] += value
            h[2] = min(h[2], value)
            h[3] = max(h[3], value)


def get(name: str, **labels) -> float:
    """Current counter value (0 when never incremented)."""
    with _LOCK:
        return _COUNTERS.get(_key(name, labels), 0)


def _render(k: tuple) -> str:
    name, items = k
    if not items:
        return name
    return name + "{" + ",".join(f"{a}={b}" for a, b in items) + "}"


def snapshot() -> dict:
    """The registry as plain JSON-ready dicts (always all three
    sections, so consumers can index unconditionally)."""
    with _LOCK:
        return {
            "counters": {_render(k): v for k, v in sorted(_COUNTERS.items())},
            "gauges": {_render(k): v for k, v in sorted(_GAUGES.items())},
            "histograms": {
                _render(k): {"count": h[0], "total": h[1],
                             "min": h[2], "max": h[3]}
                for k, h in sorted(_HISTS.items())
            },
        }


def delta(before: dict, after: dict) -> dict:
    """The registry movement BETWEEN two :func:`snapshot` calls, in
    snapshot shape — per-phase metric scoping for ``bench.py``: each
    opt-in phase snapshots at entry and publishes only what IT moved,
    so ``--batch`` counters cannot bleed into the ``--serve`` /
    ``--loadgen`` sub-objects. Counters and histogram count/total
    subtract (zero movement drops out); gauges are last-value-wins so
    the phase reports those it TOUCHED at their ``after`` value;
    histogram min/max cannot be un-merged and honestly report the
    window's ``after`` values only when the count moved."""
    out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
    b, a = before.get("counters", {}), after.get("counters", {})
    for key, v in a.items():
        moved = v - b.get(key, 0)
        if moved:
            out["counters"][key] = moved
    bg, ag = before.get("gauges", {}), after.get("gauges", {})
    for key, v in ag.items():
        if key not in bg or bg[key] != v:
            out["gauges"][key] = v
    bh, ah = before.get("histograms", {}), after.get("histograms", {})
    for key, h in ah.items():
        prev = bh.get(key, {"count": 0, "total": 0.0})
        moved = h["count"] - prev["count"]
        if moved:
            out["histograms"][key] = {
                "count": moved, "total": h["total"] - prev["total"],
                "min": h["min"], "max": h["max"],
            }
    return out


def reset() -> None:
    """Empty the registry (tests; fresh bench phases)."""
    with _LOCK:
        _COUNTERS.clear()
        _GAUGES.clear()
        _HISTS.clear()
        _LABELSETS.clear()
