"""Trace-file analysis: phase breakdown, hop fit, recovery/retrace summary.

Consumes the JSONL a ``MOMP_TRACE`` sink produced (``obs.trace`` schema)
and reduces it to the three questions the observability layer exists to
answer:

* **Where did the wall clock go?** — per-span-name totals against the
  wall covered by root spans (``phases``).
* **What did the ring do?** — traced attention steps, per-hop span
  counts (the ``2*(p-1)`` contract), engines seen, and an α+βn fit over
  the ``ring.hop.transfer`` (bytes, µs) rows whenever the trace carries
  at least two distinct transfer sizes — the same ``fabric.fit_alpha_beta``
  model the pingpong probe uses, now fed by production hops.
* **What went wrong and what got rebuilt?** — recovery events by stamp,
  and the ``jit.retrace{fn=...}`` counters from the last ``metrics``
  snapshot event in the stream.

Kept import-light on purpose: ``fabric`` (which pulls in jax) loads only
when a hop fit is actually computable, so ``trace_report.py --json`` on a
ring-free trace never touches the accelerator stack.
"""

from __future__ import annotations

import json


def load(path: str) -> list[dict]:
    """Parse one record per non-blank line; raise ``ValueError`` naming
    the first malformed line (a truncated tail from a killed process is
    a real signal, not something to paper over). A well-formed JSON
    object WITHOUT a ``kind`` field is a header line (external tooling
    prepends them), not corruption: it is skipped, so an empty or
    header-only file reports zero records instead of erroring."""
    records = []
    with open(path) as fd:
        for lineno, line in enumerate(fd, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(
                    f"{path}:{lineno}: not a JSON record ({e.msg})") from e
            if not isinstance(rec, dict):
                raise ValueError(
                    f"{path}:{lineno}: not a JSON object record")
            if "kind" not in rec:
                continue  # header line
            records.append(rec)
    return records


def _spans(records: list[dict], name: str | None = None) -> list[dict]:
    return [r for r in records if r.get("kind") == "span"
            and (name is None or r.get("name") == name)]


def _phase_breakdown(records: list[dict]) -> dict:
    spans = _spans(records)
    # Wall = time under root spans only; nested spans re-count their
    # parents' time, so summing every span would exceed 100%.
    wall = sum(s.get("dur", 0.0) for s in spans if s.get("parent") is None)
    phases: dict[str, dict] = {}
    for s in spans:
        ph = phases.setdefault(
            s.get("name", "?"), {"count": 0, "total_s": 0.0, "errors": 0})
        ph["count"] += 1
        ph["total_s"] += s.get("dur", 0.0)
        if "error" in s:
            ph["errors"] += 1
    for ph in phases.values():
        ph["total_s"] = round(ph["total_s"], 6)
        ph["mean_s"] = round(ph["total_s"] / ph["count"], 6)
        ph["share"] = round(ph["total_s"] / wall, 4) if wall > 0 else None
    return {"wall_s": round(wall, 6), "by_name": phases}


def _hop_fit(transfers: list[dict]) -> dict | None:
    """α+βn over (bytes, mean µs) of the transfer spans — needs two
    distinct sizes or the slope is unconstrained."""
    by_size: dict[int, list[float]] = {}
    for s in transfers:
        b = (s.get("attrs") or {}).get("bytes")
        if isinstance(b, (int, float)) and b > 0:
            by_size.setdefault(int(b), []).append(s.get("dur", 0.0) * 1e6)
    if len(by_size) < 2:
        return None
    from mpi_and_open_mp_tpu.parallel import fabric

    rows = [(b, sum(us) / len(us)) for b, us in sorted(by_size.items())]
    return fabric.fit_alpha_beta(rows).as_json()


def _attention(records: list[dict]) -> dict:
    steps = [s for s in _spans(records, "ring_attention")
             if (s.get("attrs") or {}).get("traced_dispatch")]
    whole = [s for s in _spans(records, "ring_attention")
             if not (s.get("attrs") or {}).get("traced_dispatch")]
    transfers = _spans(records, "ring.hop.transfer")
    folds = _spans(records, "ring.hop.fold")
    engines = sorted({(s.get("attrs") or {}).get("engine", "?")
                      for s in folds + steps + whole})
    hop_spans = len(transfers) + len(folds)
    return {
        "traced_steps": len(steps),
        "whole_call_spans": len(whole),
        "hop_spans": hop_spans,
        "transfer_spans": len(transfers),
        "fold_spans": len(folds),
        "hop_spans_per_step": (round(hop_spans / len(steps), 3)
                               if steps else None),
        "engines": engines,
        "hop_fit": _hop_fit(transfers),
    }


def _halo(records: list[dict]) -> dict:
    """The sharded halo-schedule summary: ``halo.overlap``/``halo.seq``
    span counts with the engine stamps seen on each, plus the exposed-
    vs-hidden transfer accounting from the LAST ``halo.ab`` event
    (``bench._sharded_ab_phase`` emits one per A/B: measured transfer
    seconds per round, the exposed remainder the overlap failed to hide,
    and their ratio as overlap efficiency)."""
    overlap = _spans(records, "halo.overlap")
    seq = _spans(records, "halo.seq")
    engines = sorted({(s.get("attrs") or {}).get("engine", "?")
                      for s in overlap + seq})
    ab = None
    for r in records:
        if r.get("kind") == "event" and r.get("name") == "halo.ab":
            ab = dict(r.get("attrs") or {})
    return {
        "overlap_spans": len(overlap),
        "seq_spans": len(seq),
        "engines": engines,
        "ab": ab,
    }


def _recoveries(records: list[dict]) -> dict:
    by_stamp: dict[str, int] = {}
    for r in records:
        if r.get("kind") == "event" and r.get("name") == "recovery":
            stamp = (r.get("attrs") or {}).get("stamp", "?")
            by_stamp[stamp] = by_stamp.get(stamp, 0) + 1
    return {"total": sum(by_stamp.values()), "by_stamp": by_stamp}


def _retraces(records: list[dict]) -> dict:
    """``jit.retrace{fn=...}`` counters from the LAST ``metrics``
    snapshot event — the registry is cumulative, so the last snapshot
    supersedes every earlier one."""
    snap = None
    for r in records:
        if r.get("kind") == "event" and r.get("name") == "metrics":
            snap = (r.get("attrs") or {}).get("snapshot")
    if not isinstance(snap, dict):
        return {}
    out = {}
    for key, val in snap.get("counters", {}).items():
        if key.startswith("jit.retrace{"):
            fn = key[len("jit.retrace{"):-1].removeprefix("fn=")
            out[fn] = val
    return out


def report_dict(records: list[dict]) -> dict:
    """The full report as JSON-ready data (``trace_report.py --json``)."""
    return {
        "records": len(records),
        "phases": _phase_breakdown(records),
        "attention": _attention(records),
        "halo": _halo(records),
        "recoveries": _recoveries(records),
        "retraces": _retraces(records),
    }


def _track_of(rec: dict, by_id: dict) -> int:
    """The root ancestor's id — one Perfetto track per root span, so
    time-enclosure nesting on a track reproduces span parentage exactly
    (spans of one thread strictly nest; unrelated roots never share a
    track). An orphaned parent id (truncated trace) roots its subtree."""
    seen = set()
    cur = rec
    while True:
        parent = cur.get("parent")
        if parent is None or parent not in by_id or parent in seen:
            return cur.get("id", 0)
        seen.add(parent)
        cur = by_id[parent]


def to_chrome(records: list[dict]) -> dict:
    """Chrome trace-event JSON from obs records — opens in Perfetto /
    chrome://tracing, so ring-hop and batch-serve timelines are browsable
    instead of grep-able.

    Spans become complete ("X") events with microsecond ts/dur; events
    become thread-scoped instants ("i"). Span ids and parent ids ride in
    ``args`` so tooling can verify nesting against the source parentage
    (the CI chrome smoke does).
    """
    spans = _spans(records)
    by_id = {r["id"]: r for r in spans if "id" in r}
    events = []
    for r in spans:
        args = dict(r.get("attrs") or {})
        args["span_id"] = r.get("id")
        args["parent"] = r.get("parent")
        if "error" in r:
            args["error"] = r["error"]
        events.append({
            "ph": "X", "cat": "span", "name": r.get("name", "?"),
            "ts": r.get("ts", 0.0) * 1e6,
            "dur": max(r.get("dur", 0.0), 0.0) * 1e6,
            "pid": r.get("pid", 0), "tid": _track_of(r, by_id),
            "args": args,
        })
    for r in records:
        if r.get("kind") != "event":
            continue
        parent = by_id.get(r.get("parent"))
        events.append({
            "ph": "i", "s": "t", "cat": "event", "name": r.get("name", "?"),
            "ts": r.get("ts", 0.0) * 1e6,
            "pid": r.get("pid", 0),
            "tid": _track_of(parent, by_id) if parent else r.get("id", 0),
            "args": dict(r.get("attrs") or {}),
        })
    events.sort(key=lambda e: e["ts"])
    # Name each process track with its host (metadata rows sort first by
    # convention; Perfetto accepts them anywhere).
    meta = [{"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
             "args": {"name": f"{host} (pid {pid})"}}
            for pid, host in sorted(
                {(r.get("pid", 0), r.get("host", "?")) for r in records})]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def render(rep: dict) -> str:
    """Text tables of :func:`report_dict` output for terminal reading."""
    lines = []
    ph = rep["phases"]
    lines.append(f"trace: {rep['records']} records, "
                 f"wall {ph['wall_s']:.3f}s under root spans")
    lines.append("")
    lines.append(f"{'span':<24}{'count':>7}{'total s':>12}"
                 f"{'mean s':>12}{'share':>8}")
    for name, row in sorted(ph["by_name"].items(),
                            key=lambda kv: -kv[1]["total_s"]):
        share = f"{row['share']:.1%}" if row["share"] is not None else "-"
        err = f"  ({row['errors']} errors)" if row["errors"] else ""
        lines.append(f"{name:<24}{row['count']:>7}{row['total_s']:>12.4f}"
                     f"{row['mean_s']:>12.6f}{share:>8}{err}")
    att = rep["attention"]
    if att["traced_steps"] or att["whole_call_spans"]:
        lines.append("")
        lines.append(
            f"attention: {att['traced_steps']} traced steps, "
            f"{att['hop_spans']} hop spans "
            f"({att['transfer_spans']} transfer + {att['fold_spans']} fold"
            + (f", {att['hop_spans_per_step']}/step"
               if att["hop_spans_per_step"] is not None else "")
            + f"), engines: {', '.join(att['engines'])}")
        if att["hop_fit"]:
            f = att["hop_fit"]
            bw = (f"{f['bandwidth_mb_s']}MB/s" if f["identifiable"]
                  else "unidentifiable(beta<=0)")
            lines.append(f"hop fit: alpha={f['alpha_us']}us bandwidth={bw} "
                         f"r2={f['r2']}")
    hal = rep.get("halo") or {}
    if hal.get("overlap_spans") or hal.get("seq_spans"):
        lines.append("")
        lines.append(
            f"halo: {hal['overlap_spans']} overlap + {hal['seq_spans']} "
            f"seq schedule spans, engines: {', '.join(hal['engines'])}")
        ab = hal.get("ab")
        if ab:
            lines.append(
                f"halo A/B: transfer={ab.get('transfer_s', 0):.6f}s/round "
                f"exposed={ab.get('exposed_s', 0):.6f}s "
                f"efficiency={ab.get('efficiency', 0):.1%}")
    rec = rep["recoveries"]
    if rec["total"]:
        lines.append("")
        lines.append(f"recoveries: {rec['total']}")
        for stamp, n in sorted(rec["by_stamp"].items()):
            lines.append(f"  {stamp}: {n}")
    if rep["retraces"]:
        lines.append("")
        lines.append("jit retraces (from last metrics snapshot):")
        for fn, n in sorted(rep["retraces"].items()):
            lines.append(f"  {fn}: {int(n)}")
    return "\n".join(lines)
