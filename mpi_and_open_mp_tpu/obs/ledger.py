"""Cross-run performance ledger: append-only JSONL, one entry per bench line.

The reference repo's cross-run record is ``times.txt`` accumulation — raw
seconds with no provenance, comparable only by whoever remembers what
machine produced each line. PR 4's spans/metrics replaced the *in-process*
half of that story; this module is the *across-runs* half: every
``bench.py`` JSON line lands here stamped with the facts the sentinel
(``analysis/regression_sentinel.py``) needs to notice when a number got
worse or an engine silently downgraded — git SHA, platform, device kind,
topology, and the configuration key. BENCH_r04/r05 recorded ~1000× slower
CPU-fallback numbers with nothing watching; with the ledger, that is a
one-command verdict.

Entry schema, one JSON object per line (append-only; multiple processes
may share one file, same discipline as the ``MOMP_TRACE`` sink)::

    {"schema": "momp-ledger/1", "ts": <epoch sec>, "git_sha": ...,
     "source": "bench.py" | "backfill:<file>#L<n>" | ...,
     "platform": "tpu"|"cpu", "device_kind": ..., "topology": "tpu:1",
     "key": {"metric", "topology", "shape", "dtype", "steps", "batch",
             "engine"},
     "record": {...the full bench JSON line...}}

The query key is (topology, shape, dtype, batch, engine) plus the metric
name — :func:`config_key` renders any subset of it as a stable string so
baselines group per configuration. Keyed lookups deliberately support
*subsets*: the sentinel matches on the workload fields only
(metric/shape/dtype/steps/batch) so a TPU→CPU fallback run still lands in
the same comparison group as its real-chip baseline instead of escaping
into a fresh key.

Everything here is stdlib-only (no jax import): the sentinel and the
queue-loop gate must run on a host that is *not* allowed to touch the
accelerator.
"""

from __future__ import annotations

import json
import os
import subprocess
import time

ENV = "MOMP_LEDGER"

#: Canonical key-field order; ``config_key`` renders them in this order.
#: ``batch_pack_layout`` joined in PR 10: a bitsliced and a cell-packed
#: run of the same stack are different configurations (the sentinel
#: treats bitsliced → cell-packed as a provenance downgrade, same as
#: pallas → jnp). ``resident`` joined in PR 12: a device-resident
#: session-pool run and a ship-boards-every-call run measure different
#: serving disciplines, so they must never share a baseline group.
#: ``workload`` joined in PR 13 (the stencil spec subsystem): a heat run
#: and a life run at the same shape are different rules entirely —
#: entries stamped before the field existed default to "life", which is
#: exactly what they ran. ``plan`` joined in PR 14 (the autotuner): a
#: line measured under a persisted/tuned plan ({store, fresh}) and a
#: heuristic-routed line are different dispatch decisions — the sentinel
#: treats tuned -> heuristic as a provenance downgrade. ``halo`` joined
#: in PR 15 (persistent halo plans): the sharded halo schedule stamp
#: ({overlap:*, seq:*}) — the sentinel treats overlap -> seq as a
#: provenance downgrade (the kill switch silently left on is exactly the
#: regression this catches). ``sparse`` joined in PR 16 (sparse x
#: sharded): the active-tile engine stamp for whichever sparse phase the
#: line ran ({sparse-sharded:*, sparse:*, dense:*}) — the sentinel
#: treats sparse-sharded -> dense:sharded (MOMP_SPARSE_SHARDED=0 left
#: on) as a provenance downgrade. ``engine_family`` joined in PR 20
#: (wide-radius engine families): the aggregation family the line's
#: stencil phase ran ({offset, sep, fft}) — the sentinel treats
#: fft/sep -> offset on the same workload (MOMP_ENGINE_FAMILY=offset
#: left pinned) as a provenance downgrade.
KEY_FIELDS = ("metric", "topology", "shape", "dtype", "steps", "batch",
              "batch_pack_layout", "resident", "workload", "plan",
              "halo", "sparse", "engine_family", "engine")

_GIT_SHA: str | None = None


def ledger_path(default: str | None = None) -> str | None:
    """The ledger path from ``MOMP_LEDGER``, else ``default``."""
    return os.environ.get(ENV) or default


def git_sha(cwd: str | None = None) -> str:
    """The repo HEAD SHA (short), cached; ``"unknown"`` outside a repo."""
    global _GIT_SHA
    if _GIT_SHA is None:
        if cwd is None:
            cwd = os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
        try:
            _GIT_SHA = subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"], cwd=cwd,
                capture_output=True, text=True, timeout=10,
            ).stdout.strip() or "unknown"
        except (OSError, subprocess.SubprocessError):
            _GIT_SHA = "unknown"
    return _GIT_SHA


def _shape_str(record: dict) -> str:
    board = record.get("board")
    if (isinstance(board, (list, tuple)) and len(board) == 2
            and all(isinstance(b, int) for b in board)):
        return f"{board[0]}x{board[1]}"
    return "?"


def stamp(record: dict, *, source: str = "bench.py",
          platform: str | None = None, device_kind: str | None = None,
          device_count: int | None = None, ts: float | None = None,
          sha: str | None = None) -> dict:
    """Wrap one bench JSON line as a ledger entry.

    ``platform``/``device_kind``/``device_count`` come from the caller
    (who has jax in hand); when omitted they fall back to what the record
    itself carries so backfilled lines stay honest about what was and was
    not recorded at the time.
    """
    platform = platform or record.get("platform") or record.get(
        "backend") or "?"
    topology = f"{platform}:{device_count if device_count else '?'}"
    key = {
        "metric": record.get("metric", "?"),
        "topology": topology,
        "shape": _shape_str(record),
        "dtype": record.get("dtype", "?"),
        "steps": record.get("steps", "?"),
        "batch": record.get("batch", 0),
        # "-" for non-batched lines (no stack, no pack layout); batched
        # lines carry the closed vocabulary {cell-packed, bitsliced}.
        "batch_pack_layout": record.get("batch_pack_layout", "-"),
        # "-" for lines without a sessions phase; "pool" when the record
        # carries device-resident session-pool measurements.
        "resident": record.get("resident", "-"),
        # Pre-stencil lines carry no workload field: life, exactly.
        "workload": record.get("workload", "life"),
        # "-" for lines that never consulted the autotuner; tuned lines
        # carry the closed vocabulary {heuristic, fresh, store}.
        "plan": record.get("plan_source", "-"),
        # "-" for lines without a sharded A/B; scheduled lines carry the
        # haloplan engine stamp ({overlap:*, seq:*}).
        "halo": record.get("sharded_halo", "-"),
        # "-" for lines without a sparse phase; the sparse-sharded A/B
        # stamp wins over the single-device one when both phases ran
        # (it is the composed engine this key exists to pin).
        "sparse": record.get("sparse_sharded_engine",
                             record.get("sparse_engine", "-")),
        # "-" for lines without a stencil engine-family phase; family
        # lines carry the closed vocabulary {offset, sep, fft}.
        "engine_family": record.get("engine_family", "-"),
        "engine": record.get("impl", "?"),
    }
    return {
        "schema": "momp-ledger/1",
        "ts": time.time() if ts is None else ts,
        "git_sha": sha if sha is not None else git_sha(),
        "source": source,
        "platform": platform,
        "device_kind": device_kind or record.get("device_kind")
        or "unrecorded",
        "topology": topology,
        "key": key,
        "record": record,
    }


def append(entry: dict, path: str) -> None:
    """Append one entry as one JSON line (parent dirs created)."""
    outdir = os.path.dirname(path)
    if outdir:
        os.makedirs(outdir, exist_ok=True)
    with open(path, "a") as fd:
        fd.write(json.dumps(entry) + "\n")


def load(path: str) -> list[dict]:
    """Parse one entry per non-blank line; raise ``ValueError`` naming the
    first malformed line (same discipline as ``obs.report.load`` — a
    truncated tail from a killed process is a signal, not noise)."""
    entries = []
    with open(path) as fd:
        for lineno, line in enumerate(fd, 1):
            line = line.strip()
            if not line:
                continue
            try:
                entry = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(
                    f"{path}:{lineno}: not a JSON record ({e.msg})") from e
            if not isinstance(entry, dict) or "record" not in entry:
                raise ValueError(
                    f"{path}:{lineno}: entry without a 'record' field")
            entries.append(entry)
    return entries


#: Key fields whose absence means "not applicable" rather than
#: "unrecorded": entries stamped before the field joined KEY_FIELDS must
#: keep matching new lines that carry the explicit "-" placeholder.
_KEY_DEFAULTS = {"batch_pack_layout": "-", "resident": "-",
                 "workload": "life", "plan": "-", "halo": "-",
                 "sparse": "-", "engine_family": "-"}


def config_key(entry: dict, fields: tuple[str, ...] = KEY_FIELDS) -> str:
    """Render an entry's key (or any subset of it) as a stable string,
    e.g. ``metric=life_steady_cups_p46gun_big|shape=500x500|batch=0``."""
    key = entry.get("key") or {}
    return "|".join(
        f"{f}={key.get(f, _KEY_DEFAULTS.get(f, '?'))}" for f in fields)


def query(entries: list[dict], **where) -> list[dict]:
    """Entries whose key matches every ``field=value`` given (values
    compared as strings, chronological order preserved)."""
    out = []
    for e in entries:
        key = e.get("key") or {}
        if all(str(key.get(f, "?")) == str(v) for f, v in where.items()):
            out.append(e)
    return out
