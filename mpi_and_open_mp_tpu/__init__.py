"""mpi_and_open_mp_tpu — a TPU-native distributed stencil/HPC framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of the MPI
coursework repo ``kekoveca/MPI-and-Open-MP``:

* Conway's Game of Life on a periodic 2-D torus, distributed over a
  ``jax.sharding.Mesh`` under 1-D row, 1-D column, and 2-D Cartesian
  decompositions (reference: ``3-life/life_mpi.c``, ``4-life/life_mpi.c``,
  ``6-cartesian/life_cart.c``) with ``lax.ppermute`` halo exchange instead of
  blocking ``MPI_Send``/``MPI_Recv``.
* Distributed trapezoidal quadrature with ``lax.psum`` reductions
  (reference: ``1-integral/integral.c``).
* A fabric latency/bandwidth micro-benchmark probing ICI/DCN via timed
  collectives (reference: ``2-network-params/mpi_send_recv.c``).
* The reference's measurement harness contracts: ``.cfg`` inputs,
  elapsed-seconds stdout, VTK snapshots, ``times.txt`` accumulation.
* Beyond the reference: a first-class long-context sequence-parallel
  attention layer (ring + Ulysses + single-device ``flash_attention``,
  GQA/MQA, flash ``custom_vjp`` backwards on both the local and the
  multi-device ring paths, a striped/zigzag causal-load-balanced ring
  layout, TPU dispatch to the bundled Pallas flash kernel with
  chip-validated explicit blocks — ``parallel.context``), bit-packed
  temporal-blocking Life kernels (one collective round per 128 steps —
  ``ops.bitlife``), Orbax checkpoint/resume, and a multi-host
  ``jax.distributed`` runtime.

Subpackages
-----------
``ops``       compute kernels (jnp stencils, Pallas kernels, quadrature)
``parallel``  device mesh topology, halo exchange, collectives, fabric probe
``models``    full simulations wiring config -> sharded state -> run loop -> IO
``utils``     config loading, VTK IO, timing, native-library bindings
"""

__version__ = "0.2.0"

from mpi_and_open_mp_tpu.utils.config import LifeConfig, load_config  # noqa: F401
