"""Device mesh topology — the TPU-native replacement for MPI communicators.

The reference derives its process topology from ``MPI_COMM_WORLD``:
a flat rank list for the 1-D strip decompositions
(``/root/reference/3-life/life_mpi.c:101-103``) and a periodic 2-D grid via
``MPI_Dims_create`` + ``MPI_Cart_create``
(``/root/reference/6-cartesian/life_cart.c:117-121``). Here the same roles
are played by a ``jax.sharding.Mesh``: 1-D meshes over axis ``"y"`` or
``"x"``, and a 2-D ``("y", "x")`` mesh. Periodicity lives in the
``ppermute`` permutations (see ``parallel.halo``), not the mesh itself —
every mesh axis is a ring when the halo code says so.

Axis naming convention (used across the whole framework): ``"y"`` shards the
row dimension (axis 0 of the ``(ny, nx)`` board), ``"x"`` shards the column
dimension (axis 1).
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh

# Classic GSPMD propagation (Auto) rather than sharding-in-types (Explicit,
# the jax>=0.9 make_mesh default): the roll-based global step relies on XLA
# propagating shardings through circular shifts of arbitrary (uneven) sizes.
# ``AxisType`` only exists from jax 0.4.38ish onward (and ``make_mesh`` only
# grew the ``axis_types`` kwarg alongside it); on older jax every mesh axis
# IS implicitly Auto, so the portable form is: pass ``axis_types`` only when
# the installed jax knows the enum, otherwise rely on the implicit default.
try:  # pragma: no cover - exercised as one branch per installed jax
    from jax.sharding import AxisType
except ImportError:  # jax <= 0.4.37: Auto semantics are the only semantics
    AxisType = None

AXIS_Y = "y"
AXIS_X = "x"

# ``jax.shard_map`` is also a recent promotion: on jax <= 0.4.37 it lives at
# ``jax.experimental.shard_map.shard_map`` and spells the replication check
# ``check_rep`` instead of ``check_vma``. Every shard_map in this codebase
# goes through this wrapper so call sites stay version-agnostic.
if hasattr(jax, "shard_map"):  # pragma: no cover - one branch per jax

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        """Version-portable ``jax.shard_map``."""
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)

else:  # pragma: no cover - one branch per jax
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        """Version-portable ``jax.shard_map`` (pre-0.4.38 spelling)."""
        return _experimental_shard_map(f, mesh=mesh, in_specs=in_specs,
                                       out_specs=out_specs, check_rep=check_vma)


def _auto_mesh(shape: tuple[int, ...], names: tuple[str, ...]) -> Mesh:
    """``jax.make_mesh`` with Auto axis semantics on every jax version."""
    if AxisType is None:
        return jax.make_mesh(shape, names)
    return jax.make_mesh(shape, names,
                         axis_types=tuple(AxisType.Auto for _ in names))


def dims_create(n: int, ndims: int = 2) -> tuple[int, ...]:
    """Balanced factorisation of ``n`` over ``ndims`` mesh axes.

    Same contract as ``MPI_Dims_create`` (used by the reference at
    ``6-cartesian/life_cart.c:118``): dimensions as close to each other as
    possible, in non-increasing order. Deterministic greedy algorithm:
    repeatedly peel the largest factor ≤ the remaining ``ndims``-th root.
    """
    if n < 1 or ndims < 1:
        raise ValueError(f"dims_create({n}, {ndims})")
    dims = []
    remaining = n
    for d in range(ndims, 0, -1):
        if d == 1:
            dims.append(remaining)
            break
        # Largest divisor of `remaining` that is <= remaining ** (1/d),
        # searched downward from the integer root.
        target = round(remaining ** (1.0 / d))
        best = 1
        for cand in range(target, 0, -1):
            if remaining % cand == 0:
                best = cand
                break
        # Try upward too: pick whichever divisor is closest to the root.
        for cand in range(target + 1, remaining + 1):
            if remaining % cand == 0:
                if abs(cand - remaining ** (1.0 / d)) < abs(best - remaining ** (1.0 / d)):
                    best = cand
                break
        dims.append(best)
        remaining //= best
    return tuple(sorted(dims, reverse=True))


def decomposition(n: int, p: int, k: int) -> tuple[int, int]:
    """Reference shard map: rank ``k`` of ``p`` owns ``[start, stop)`` of ``n``.

    Floor-chunking with the LAST shard absorbing the remainder — the exact
    semantics of the reference's ``decomposition()``
    (``3-life/life_mpi.c:178-183``, identical in ``4-life``/``5-gather``/
    ``6-cartesian``). Used for host-side partitioning bookkeeping and for
    documenting parity; on-device sharding uses even blocks (XLA requirement)
    with the global roll-based step handling any residue.
    """
    chunk = n // p
    start = k * chunk
    stop = n if k == p - 1 else (k + 1) * chunk
    return start, stop


def make_mesh_1d(n: int | None = None, axis: str = AXIS_Y) -> Mesh:
    """1-D device mesh over ``n`` devices (default: all local devices)."""
    if n is None:
        n = len(jax.devices())
    return _auto_mesh((n,), (axis,))


def make_mesh_2d(py: int | None = None, px: int | None = None) -> Mesh:
    """2-D ``("y", "x")`` device mesh.

    With no arguments, factorises the full device count like
    ``MPI_Dims_create`` (``6-cartesian/life_cart.c:117-118``).
    """
    if py is None and px is None:
        py, px = dims_create(len(jax.devices()), 2)
    elif py is None or px is None:
        raise ValueError("pass both py and px, or neither")
    return _auto_mesh((py, px), (AXIS_Y, AXIS_X))
