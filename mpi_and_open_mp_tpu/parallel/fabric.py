"""Fabric latency/bandwidth probe — the ICI/DCN analogue of the reference's
MPI ping-pong benchmark (``/root/reference/2-network-params/mpi_send_recv.c``).

The reference times 10⁵ blocking Send/Recv round trips between two ranks for
message sizes 1..10⁶ B and prints ``size,half-RTT µs`` CSV rows
(``mpi_send_recv.c:20-39``); the same binary at two placements (1 node vs 2
nodes) characterises shared-memory vs NIC transport. Here the transport is
the accelerator fabric: a timed ``lax.ppermute`` ring shift of an N-byte
buffer over a mesh axis, ``reps`` rounds fused in one jitted ``fori_loop``
(so dispatch overhead amortises exactly like the reference's tight loop).
One hop of a ring permute is the ppermute analogue of a half round trip.

The α+βn model fit (``plot.ipynb`` cells 5-6) lives in ``fit_alpha_beta``:
α = latency intercept, 1/β = asymptotic bandwidth.
"""

from __future__ import annotations

import functools
import time
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mpi_and_open_mp_tpu.parallel import mesh as mesh_lib
from mpi_and_open_mp_tpu.parallel.halo import axis_size, ring_perm

# Message sizes in bytes: 10^0 .. 10^6, matching mpi_send_recv.c:22.
DEFAULT_SIZES = tuple(10**k for k in range(7))


@functools.partial(jax.jit, static_argnames=("axis", "reps", "mesh"))
def _ring_shift_loop(buf: jnp.ndarray, *, axis: str, reps: int, mesh: Mesh):
    """``reps`` sequential one-hop ring shifts of each device's buffer."""

    def shifted(b):
        p = axis_size(axis)
        return lax.ppermute(b, axis, ring_perm(p, 1))

    smapped = mesh_lib.shard_map(
        lambda b: lax.fori_loop(0, reps, lambda _, x: shifted(x), b),
        mesh=mesh,
        in_specs=P(axis),
        out_specs=P(axis),
        check_vma=False,
    )
    return smapped(buf)


def ping(mesh: Mesh, msg_bytes: int, reps: int = 100) -> float:
    """Mean seconds per one-hop transfer of a ``msg_bytes`` buffer.

    Each device holds its own ``msg_bytes`` payload (int8), so one round
    moves ``msg_bytes`` over every link in parallel — the fabric analogue of
    the reference's 2-rank half-RTT.
    """
    axis = next(iter(mesh.shape))
    p = mesh.size
    n = max(1, msg_bytes)
    buf = jnp.zeros((p * n,), dtype=jnp.int8)
    buf = jax.device_put(buf, NamedSharding(mesh, P(axis)))
    from mpi_and_open_mp_tpu.utils.timing import anchor_sync

    # Warm-up: compile + first transfer.
    anchor_sync(_ring_shift_loop(buf, axis=axis, reps=reps, mesh=mesh),
                fetch_all=True)
    # Chaos hook (robust.chaos): an injected host-side delay INSIDE the
    # timed bracket simulates a congested fabric / slow relay hop, so
    # harness code consuming these probes (fit sanity, CSV writers) can
    # be tested against pathological timings. No-op when MOMP_CHAOS is
    # unset.
    from mpi_and_open_mp_tpu.robust import chaos

    delay = chaos.dispatch_delay()
    t0 = time.perf_counter()
    if delay:
        time.sleep(delay)
    out = _ring_shift_loop(buf, axis=axis, reps=reps, mesh=mesh)
    # Anchored one-element fetch, not bare block_until_ready: the latter
    # is a no-op on some platforms (observed on the axon TPU tunnel);
    # the anchor reads a locally addressable shard, so it also works on
    # multi-process meshes where a global fetch is impossible.
    anchor_sync(out, fetch_all=True)
    elapsed = time.perf_counter() - t0
    return elapsed / reps


def sweep(
    mesh: Mesh | None = None,
    sizes: tuple[int, ...] = DEFAULT_SIZES,
    reps: int = 100,
) -> list[tuple[int, float]]:
    """Probe each message size; returns ``(bytes, microseconds_per_hop)``
    rows — the reference's CSV schema (``mpi_send_recv.c:38``)."""
    if mesh is None:
        mesh = mesh_lib.make_mesh_1d(axis="i")
    return [(s, ping(mesh, s, reps) * 1e6) for s in sizes]


def write_csv(path: str, rows: list[tuple[int, float]]) -> None:
    """``size,time`` CSV compatible with the reference's ``out_*.csv`` files
    consumed by its ``plot.ipynb`` analysis."""
    with open(path, "w") as fd:
        fd.write("size,time\n")
        for s, us in rows:
            fd.write(f"{s},{us:.6f}\n")


class Fit(NamedTuple):
    """α+βn fit result with its quality: consumers must be able to tell a
    measured bandwidth from fit noise (a loopback Gloo probe once shipped
    an artifact reading "infinite bandwidth" off a β ≤ 0 slope)."""

    alpha_us: float
    bandwidth_mb_s: float  # math.inf when unidentifiable — check the flag
    r2: float  # of the unconstrained linear fit
    identifiable: bool  # False when β ≤ 0 (noise-dominated probe)

    def render(self) -> str:
        """The one rendering every consumer (CLI stderr, fit.txt) uses,
        so artifacts and logs cannot disagree on the flag format."""
        bw = (f"{self.bandwidth_mb_s:.1f}MB/s" if self.identifiable
              else "unidentifiable(beta<=0)")
        return f"alpha={self.alpha_us:.3f}us bandwidth={bw} r2={self.r2:.3f}"

    def as_json(self) -> dict:
        """JSON-ready view for machine consumers (pingpong's fit line,
        trace_report's hop fit). An unidentifiable fit must NOT emit the
        internal ``inf`` sentinel — ``json.dumps`` would write bare
        ``Infinity``, which strict parsers reject — so bandwidth/beta
        become ``None``/``0.0`` there and the flag carries the verdict."""
        # bandwidth is 1/β with β in µs/byte (bytes/µs ≡ MB/s numerically).
        beta = (1.0 / self.bandwidth_mb_s) if self.identifiable else 0.0
        return {
            "alpha_us": round(float(self.alpha_us), 6),
            "beta_us_per_byte": round(float(beta), 12),
            "bandwidth_mb_s": (round(float(self.bandwidth_mb_s), 3)
                               if self.identifiable else None),
            "r2": round(float(self.r2), 6),
            "identifiable": bool(self.identifiable),
        }


def fit_alpha_beta(rows: list[tuple[int, float]]) -> Fit:
    """Linear model t = α + β·n over the probe rows (times in µs).

    Returns :class:`Fit` — the latency intercept ``alpha_us`` and the 1/β
    asymptotic bandwidth, as in the reference's ``plot.ipynb`` cell 5
    ``np.polyfit(buffer_size, time, 1)`` fit, plus the fit's R² and an
    ``identifiable`` flag. A noise-dominated probe can fit β ≤ 0 (observed
    on loopback Gloo): β is then clamped to 0 — α degrades to the mean
    latency, bandwidth is reported as ``inf`` with ``identifiable=False``,
    and renderers should print the flag, not the number.
    """
    sizes = np.array([r[0] for r in rows], dtype=np.float64)
    times = np.array([r[1] for r in rows], dtype=np.float64)
    beta, alpha = np.polyfit(sizes, times, 1)
    ss_tot = float(((times - times.mean()) ** 2).sum())
    ss_res = float(((times - (alpha + beta * sizes)) ** 2).sum())
    r2 = 1.0 - ss_res / ss_tot if ss_tot > 0 else 1.0
    if beta <= 0:
        # Constrained refit with β = 0: the best constant model.
        return Fit(float(times.mean()), float("inf"), r2, False)
    return Fit(float(alpha), float(1.0 / beta), r2, True)
