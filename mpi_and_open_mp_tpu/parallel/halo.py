"""Halo (ghost-cell) exchange over mesh axes via ``lax.ppermute``.

The TPU-native replacement for the reference's blocking ghost-row
``MPI_Send``/``MPI_Recv`` pairs (``/root/reference/3-life/life_mpi.c:198-209``
for 1-D rows, ``4-life/life_mpi.c:197-208`` for strided columns,
``6-cartesian/life_cart.c:225-279`` for the 2-D row/column/corner sequence).

Key differences by design:

* ``ppermute`` is a deterministic collective routed over ICI — there is no
  eager-protocol deadlock hazard (the reference's simultaneous blocking sends
  only work for small messages; see SURVEY §2 quirks).
* Derived datatypes disappear: a "strided column" is just a slice of the
  shard; XLA owns the layout.
* Corners come for free by sequencing the two axis exchanges — pad x first,
  then exchange the *already-padded* rows along y, exactly the two-phase
  trick the reference implements manually at ``life_cart.c:257-279``.

All functions here must be called inside ``shard_map`` with the named axis
in scope. Ghost depth ``k > 1`` enables multi-step halo fusion: exchange a
depth-``k`` halo once, then take ``k`` local stencil steps before the next
exchange round.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def ring_perm(p: int, shift: int = 1) -> list[tuple[int, int]]:
    """Permutation sending each ring member's value to ``(i + shift) % p``."""
    return [(i, (i + shift) % p) for i in range(p)]


def _axis_size(axis_name: str) -> int:
    return lax.axis_size(axis_name)


def halo_pad_y(block: jnp.ndarray, axis_name: str = "y", depth: int = 1) -> jnp.ndarray:
    """Pad axis 0 of a shard with ghost rows from its ring neighbours.

    Returns ``(h + 2*depth, w)``: ``depth`` rows from the previous shard on
    top, ``depth`` rows from the next shard at the bottom. With a single
    shard on the axis this degenerates to a torus self-wrap.
    """
    p = _axis_size(axis_name)
    # My top ghost rows are the *last* rows of my predecessor: everyone
    # sends their bottom edge forward around the ring.
    top = lax.ppermute(block[-depth:, :], axis_name, ring_perm(p, 1))
    bot = lax.ppermute(block[:depth, :], axis_name, ring_perm(p, -1))
    return jnp.concatenate([top, block, bot], axis=0)


def halo_pad_x(block: jnp.ndarray, axis_name: str = "x", depth: int = 1) -> jnp.ndarray:
    """Pad axis 1 of a shard with ghost columns from its ring neighbours.

    The reference needed ``MPI_Type_vector`` strided datatypes for this
    (``4-life/life_mpi.c:106-109``); here it is a slice + ``ppermute``.
    """
    p = _axis_size(axis_name)
    left = lax.ppermute(block[:, -depth:], axis_name, ring_perm(p, 1))
    right = lax.ppermute(block[:, :depth], axis_name, ring_perm(p, -1))
    return jnp.concatenate([left, block, right], axis=1)


def halo_pad_2d(
    block: jnp.ndarray,
    axis_y: str = "y",
    axis_x: str = "x",
    depth: int = 1,
) -> jnp.ndarray:
    """Full 2-D halo including corners, by sequential axis exchange.

    Phase 1 pads columns (x axis); phase 2 exchanges rows of the x-padded
    block, so the row ghosts already carry the corner cells — mirroring the
    reference's exchange order at ``6-cartesian/life_cart.c:275-279``.
    Returns ``(h + 2*depth, w + 2*depth)``.
    """
    padded_x = halo_pad_x(block, axis_x, depth)
    return halo_pad_y(padded_x, axis_y, depth)
