"""Halo (ghost-cell) exchange over mesh axes via ``lax.ppermute``.

The TPU-native replacement for the reference's blocking ghost-row
``MPI_Send``/``MPI_Recv`` pairs (``/root/reference/3-life/life_mpi.c:198-209``
for 1-D rows, ``4-life/life_mpi.c:197-208`` for strided columns,
``6-cartesian/life_cart.c:225-279`` for the 2-D row/column/corner sequence).

Key differences by design:

* ``ppermute`` is a deterministic collective routed over ICI — there is no
  eager-protocol deadlock hazard (the reference's simultaneous blocking sends
  only work for small messages; see SURVEY §2 quirks).
* Derived datatypes disappear: a "strided column" is just a slice of the
  shard; XLA owns the layout.
* Corners come for free by sequencing the two axis exchanges — pad x first,
  then exchange the *already-padded* rows along y, exactly the two-phase
  trick the reference implements manually at ``life_cart.c:257-279``.

All functions here must be called inside ``shard_map`` with the named axis
in scope. Ghost depth ``k > 1`` enables multi-step halo fusion: exchange a
depth-``k`` halo once, then take ``k`` local stencil steps before the next
exchange round.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def ring_perm(p: int, shift: int = 1) -> list[tuple[int, int]]:
    """Permutation sending each ring member's value to ``(i + shift) % p``."""
    return [(i, (i + shift) % p) for i in range(p)]


def axis_size(axis_name: str) -> int:
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    # jax <= 0.4.37 has no lax.axis_size; core.axis_frame(name) IS the
    # static size there (trace_ctx.axis_env.axis_size).
    return jax.core.axis_frame(axis_name)


_axis_size = axis_size


def _chaos_ghost(ghost: jnp.ndarray) -> jnp.ndarray:
    """Trace-time chaos hook (``robust.chaos``): with no active
    ``MOMP_CHAOS`` halo fault the ghost block passes through untouched and
    no injection ops enter the program — this body runs only while
    tracing, so the check costs nothing per step. A corrupted/dropped
    ghost here is what the ``LifeSim`` consistency probe must catch.
    Every ghost route funnels through this hook — including the packed
    ``pad > 0`` frame paths, which wrap their INCOMING ghost block only
    (the same-direction permute also refreshes the wrap shard's mirror
    region from live data; corrupting that write would alter real board
    state, which chaos must never do)."""
    from mpi_and_open_mp_tpu.robust import chaos

    spec = chaos.halo_ghost_spec()
    if spec is None:
        return ghost
    return chaos.corrupt_ghost(ghost, spec)


def _note_exchange(kind: str, axis_name: str) -> None:
    """Trace-time metrics hook (``obs.metrics``): counts halo exchanges
    TRACED, not executed — like :func:`_chaos_ghost`, these bodies run
    only while XLA traces the program, so per-step execution counts are
    not host-observable from in here. A traced-exchange count per
    kind/axis is still the useful signal: it is the retrace-style "how
    many distinct exchange programs were built" number, and zero of them
    means the sharded path never engaged at all."""
    from mpi_and_open_mp_tpu.obs import metrics

    metrics.inc("halo.exchange.traced", kind=kind, axis=axis_name)


def halo_pad_y(block: jnp.ndarray, axis_name: str = "y", depth: int = 1) -> jnp.ndarray:
    """Pad axis 0 of a shard with ghost rows from its ring neighbours.

    Returns ``(h + 2*depth, w)``: ``depth`` rows from the previous shard on
    top, ``depth`` rows from the next shard at the bottom. With a single
    shard on the axis this degenerates to a torus self-wrap.

    Row/column axes are the LAST TWO axes — leading channel axes (multi-
    field stencils like gray_scott) ride through untouched, and ``depth``
    is the stencil radius times any fuse depth, so every stencil spec
    shares this one exchange (dtype never appears: ``ppermute`` moves
    whatever the slice holds).
    """
    _note_exchange("y", axis_name)
    p = _axis_size(axis_name)
    # My top ghost rows are the *last* rows of my predecessor: everyone
    # sends their bottom edge forward around the ring.
    top = _chaos_ghost(
        lax.ppermute(block[..., -depth:, :], axis_name, ring_perm(p, 1)))
    bot = lax.ppermute(block[..., :depth, :], axis_name, ring_perm(p, -1))
    return jnp.concatenate([top, block, bot], axis=-2)


def halo_pad_x(block: jnp.ndarray, axis_name: str = "x", depth: int = 1) -> jnp.ndarray:
    """Pad axis 1 of a shard with ghost columns from its ring neighbours.

    The reference needed ``MPI_Type_vector`` strided datatypes for this
    (``4-life/life_mpi.c:106-109``); here it is a slice + ``ppermute``.
    Last-axis columns; leading channel axes ride along (see
    :func:`halo_pad_y` for the radius/dtype-generic contract).
    """
    _note_exchange("x", axis_name)
    p = _axis_size(axis_name)
    left = _chaos_ghost(
        lax.ppermute(block[..., -depth:], axis_name, ring_perm(p, 1)))
    right = lax.ppermute(block[..., :depth], axis_name, ring_perm(p, -1))
    return jnp.concatenate([left, block, right], axis=-1)


def packed_halo_y(
    e: jnp.ndarray, axis_name: str = "y", h: int = 4, *, pad: int = 0
) -> jnp.ndarray:
    """y halo of a bit-packed frame shard (word rows x cell columns).

    ``h`` ghost words per side travel the ring; when the frame carries
    ``pad`` mirror rows (board height padded to 32*py alignment — see
    ``ops.bitlife.plan_sharded_bits``) the wrap edges are funnel-shifted
    onto the LOGICAL board height and the wrap shard's mirror rows are
    refreshed from the first shard's live data. ``pad == 0`` degenerates
    to :func:`halo_pad_y`. With one shard on the axis this is the local
    torus wrap, same content as ``bitlife.wrap_y_padded``.
    """
    from mpi_and_open_mp_tpu.ops import bitlife

    if pad == 0:
        return halo_pad_y(e, axis_name, h)
    _note_exchange("packed_y", axis_name)
    p = _axis_size(axis_name)
    s = h + 1 + pad // 32
    # Chaos wraps the INCOMING top ghost only (injection-point parity
    # with halo_pad_y): `dn` also refreshes the wrap shard's mirror
    # rows from live data, a write chaos must never corrupt.
    up = _chaos_ghost(lax.ppermute(e[-s:], axis_name, ring_perm(p, 1)))
    dn = lax.ppermute(e[:s], axis_name, ring_perm(p, -1))
    i = lax.axis_index(axis_name)
    # Shard 0's top ghost is board rows [ny-32h, ny) — an unaligned range
    # of the LAST shard (the frame's tail is mirror rows, not the wrap);
    # interior shards take their predecessor's word-aligned tail.
    top = jnp.where(
        i == 0,
        bitlife.take_rows(up, 32 * s - pad - 32 * h, h),
        up[s - h :],
    )
    bot = jnp.where(
        i == p - 1, bitlife.take_rows(dn, pad, h), dn[:h]
    )
    e = jnp.where(i == p - 1, bitlife.mirror_tail(e, dn, pad), e)
    return jnp.concatenate([top, e, bot], axis=0)


def packed_halo_x(
    block: jnp.ndarray, axis_name: str = "x", hx: int = 128, *, pad: int = 0
) -> jnp.ndarray:
    """x halo of a packed frame shard, ``hx`` ghost columns per side.

    Column-granular twin of :func:`packed_halo_y`: with ``pad`` mirror
    columns (board width padded to the lane pitch) the wrap edges are
    slid onto the logical board width and the wrap shard's mirror
    columns are refreshed; ``pad == 0`` degenerates to
    :func:`halo_pad_x`. Packed columns are whole cell columns, so unlike
    y there is no bit-level funnel — just offset slices.
    """
    if pad == 0:
        return halo_pad_x(block, axis_name, hx)
    _note_exchange("packed_x", axis_name)
    p = _axis_size(axis_name)
    s = hx + pad
    # Chaos on the incoming left ghost only — `right` also feeds the
    # wrap shard's mirror-column refresh (see packed_halo_y).
    left = _chaos_ghost(
        lax.ppermute(block[:, -s:], axis_name, ring_perm(p, 1)))
    right = lax.ppermute(block[:, :s], axis_name, ring_perm(p, -1))
    i = lax.axis_index(axis_name)
    lb = jnp.where(i == 0, left[:, :hx], left[:, pad:])
    rb = jnp.where(i == p - 1, right[:, pad : pad + hx], right[:, :hx])
    block = jnp.where(
        i == p - 1,
        jnp.concatenate([block[:, :-pad], right[:, :pad]], axis=1),
        block,
    )
    return jnp.concatenate([lb, block, rb], axis=1)


def halo_pad_2d(
    block: jnp.ndarray,
    axis_y: str = "y",
    axis_x: str = "x",
    depth: int = 1,
) -> jnp.ndarray:
    """Full 2-D halo including corners, by sequential axis exchange.

    Phase 1 pads columns (x axis); phase 2 exchanges rows of the x-padded
    block, so the row ghosts already carry the corner cells — mirroring the
    reference's exchange order at ``6-cartesian/life_cart.c:275-279``.
    Returns ``(h + 2*depth, w + 2*depth)``.
    """
    padded_x = halo_pad_x(block, axis_x, depth)
    return halo_pad_y(padded_x, axis_y, depth)
