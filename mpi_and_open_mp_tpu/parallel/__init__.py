from mpi_and_open_mp_tpu.parallel.mesh import (  # noqa: F401
    dims_create,
    decomposition,
    make_mesh_1d,
    make_mesh_2d,
    AXIS_X,
    AXIS_Y,
)
from mpi_and_open_mp_tpu.parallel.halo import (  # noqa: F401
    halo_pad_y,
    halo_pad_x,
    halo_pad_2d,
    ring_perm,
)
from mpi_and_open_mp_tpu.parallel import fabric  # noqa: F401
from mpi_and_open_mp_tpu.parallel.context import (  # noqa: F401
    attention_reference,
    flash_attention,
    ring_attention,
    ulysses_attention,
    zigzag_order,
    zigzag_shard,
    zigzag_unshard,
    AXIS_SP,
)
