"""Long-context sequence/context parallelism: ring attention + Ulysses.

The reference's 1-D ring domain decomposition with neighbour halo exchange
(``/root/reference/3-life/life_mpi.c:103,150-176,198-209``) is structurally
the communication pattern of ring attention: a ring of peers, each owning a
contiguous slab of one long axis, streaming boundary/block state to the next
peer. This module makes that correspondence concrete — the framework's
first-class long-context layer, built on the exact same primitives as the
Life halo exchange (``parallel.halo.ring_perm`` + ``lax.ppermute`` inside
``shard_map`` over a named mesh axis):

* ``ring_attention`` — sequence-sharded attention where K/V blocks rotate
  around the ring, one hop per step, combined with an online-softmax
  (flash-style) running max/sum so the full score matrix never materialises.
  Comm rides ICI ``ppermute`` exactly like the ghost-row exchange, and is
  double-buffered: each hop issues the next rotation BEFORE folding the
  block in hand, so the transfer overlaps the MXU block matmuls; compute
  per hop is a dense (n_local x n_local) block that maps onto the MXU.
  An optional striped/zigzag token layout (``layout="zigzag"`` +
  ``zigzag_shard``/``zigzag_unshard``) balances CAUSAL work: half-block
  hops, uniform across devices, roughly halving the causal trip's
  critical path.
* ``ulysses_attention`` — the all-to-all alternative: ``lax.all_to_all``
  re-shards from sequence-parallel to head-parallel, runs full local
  attention per head group, and all-to-alls back. Two collectives total
  instead of ``p`` hops; the better choice when heads >= devices and the
  fabric favours large transposes.

Both are differentiable (static ring trip count => ``fori_loop`` lowers to
``scan``), accept any float dtype, and accumulate in float32. Parity oracle:
``attention_reference`` on the gathered sequence.
"""

from __future__ import annotations

import contextlib
import functools
import math
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mpi_and_open_mp_tpu.parallel import mesh as mesh_lib
from mpi_and_open_mp_tpu.parallel.halo import axis_size, ring_perm

AXIS_SP = "sp"

# Finite "minus infinity" for masked scores: large enough that exp() of a
# masked-vs-unmasked gap underflows to 0, small enough that NEG - NEG = 0
# stays exact (avoids the -inf - -inf = nan trap in the online softmax).
_NEG = -1e30


def attention_reference(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, causal: bool = False
) -> jnp.ndarray:
    """Plain single-device softmax attention — the parity oracle.

    Shapes ``(heads, seq, head_dim)``; float32 softmax regardless of input
    dtype, result cast back to ``q.dtype``.
    """
    h, n, d = q.shape
    s = jnp.einsum(
        "hqd,hkd->hqk", q, k, preferred_element_type=jnp.float32
    ) * (1.0 / math.sqrt(d))
    if causal:
        qpos = jnp.arange(n)[:, None]
        kpos = jnp.arange(n)[None, :]
        s = jnp.where(qpos >= kpos, s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("hqk,hkd->hqd", p, v.astype(jnp.float32))
    return o.astype(q.dtype)


# Per-device q-chunk size: when a shard's local sequence exceeds this, the
# per-hop fold scans over q chunks (padding non-multiple lengths) so the
# materialised score block is (heads, _Q_CHUNK, n_local) instead of
# (heads, n_local, n_local) — long contexts on few devices would otherwise
# OOM HBM (a 16k-token shard is a 16 GB fp32 score matrix).
_Q_CHUNK = 512


def _ring_positions(layout: str, dev, p: int, nl: int, local_rows):
    """Global token positions for local row indices of a ring shard.

    ``contiguous``: shard ``dev`` owns tokens ``[dev*nl, (dev+1)*nl)`` —
    the natural split, with causal hop skipping but causal load
    IMBALANCE (ring position p-1 computes p blocks per trip, position 0
    one — the straggler sets the pace).

    ``zigzag``: tokens are pre-sharded in ``2p`` half-chunks of
    ``nl/2``; shard ``dev`` owns half-chunks ``(dev, 2p-1-dev)`` — the
    striped/zigzag causal-balancing layout: every shard holds an equal
    share of early AND late tokens, so every hop carries the same
    half-masked block of work on every device. Use
    :func:`zigzag_shard` / :func:`zigzag_unshard` to move operands
    between natural and zigzag order.
    """
    if layout == "zigzag":
        if nl % 2:
            raise ValueError(
                f"zigzag layout needs an even local length, got {nl}")
        half = nl // 2
        lo = local_rows < half
        chunk = jnp.where(lo, dev, 2 * p - 1 - dev)
        return chunk * half + local_rows - jnp.where(lo, 0, half)
    if layout != "contiguous":
        raise ValueError(f"unknown ring layout {layout!r}")
    return dev * nl + local_rows


@functools.lru_cache(maxsize=64)
def zigzag_order(n: int, p: int):
    """Natural token position held at each zigzag slot. Pure host numpy
    (cached): ``x_zig = x[..., zigzag_order(n, p), :]`` produces the
    operand order ``ring_attention(layout="zigzag")`` expects over a
    ``p``-ring — no device ops are dispatched building it."""
    import numpy as np

    if n % (2 * p):
        raise ValueError(f"zigzag needs seq % (2*mesh) == 0, got {n}/{p}")
    nl = n // p
    half = nl // 2
    slot = np.arange(n)
    shard, r = slot // nl, slot % nl
    lo = r < half
    chunk = np.where(lo, shard, 2 * p - 1 - shard)
    out = chunk * half + np.where(lo, r, r - half)
    out.setflags(write=False)  # cached: a caller mutation must not poison it
    return out


@functools.lru_cache(maxsize=64)
def _zigzag_inverse(n: int, p: int):
    import numpy as np

    out = np.argsort(zigzag_order(n, p))
    out.setflags(write=False)
    return out


def zigzag_shard(x, p: int):
    """Permute ``(heads, seq, d)`` from natural to zigzag ring order."""
    return jnp.take(x, zigzag_order(x.shape[1], p), axis=1)


def zigzag_unshard(x, p: int):
    """Inverse of :func:`zigzag_shard` (zigzag order back to natural)."""
    return jnp.take(x, _zigzag_inverse(x.shape[1], p), axis=1)


def _mask_from_pos(qpos, kpos, n: int | None, causal: bool):
    """Boolean ``(nq, nk)`` allow-mask from position vectors: ``kpos < n``
    validity (padding) when ``n`` is given, causality when ``causal`` —
    or None when everything is allowed."""
    valid = None
    if n is not None:
        valid = kpos[None, :] < n
    if causal:
        c = qpos[:, None] >= kpos[None, :]
        valid = c if valid is None else valid & c
    return valid


@functools.partial(jax.checkpoint, static_argnums=(5, 6))
def _block_update(q32, k, v, qpos, kpos, n, causal, o, m, l):
    """One online-softmax accumulation of a K/V block into (o, m, l).

    The allow-mask is built INSIDE from the ``qpos``/``kpos`` position
    vectors (``n`` = valid k length for padding, or None; ``causal``
    static). Running state: ``o`` (hq, nq, d) unnormalised output, ``m``
    (hq, nq) running max, ``l`` (hq, nq) running denominator — all
    float32.

    Rematerialised (``jax.checkpoint``): reverse-mode would otherwise
    store every block's softmax weights — O(seq²) residuals across the
    scan/ring — where recomputing them in the backward pass keeps
    training-style gradients O(chunk x seq) like the forward (the flash
    attention backward trick). Measured: a causal 16k-token backward on
    one chip OOMs HBM without this and runs with it. Building the mask
    in here (rather than passing it) matters for the same reason: a
    passed mask is a checkpoint residual — O(hq·nq·nk) bools per block
    stacked across the ring/scan — where the position vectors are O(n).
    (Neither production path differentiates through this any more: the
    local chunked path has ``_flash_chunked_bwd`` and the multi-device
    ring has ``_ring_flash_bwd``; the remat decorator remains as a
    safety net for any future caller that autodiffs a fold directly.)
    """
    d = q32.shape[-1]
    mask = _mask_from_pos(qpos, kpos, n, causal)
    s = jnp.einsum(
        "hqd,hkd->hqk", q32, k.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ) * (1.0 / math.sqrt(d))
    if mask is not None:
        s = jnp.where(mask, s, _NEG)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    p = jnp.exp(s - m_new[..., None])
    if mask is not None:
        p = p * mask  # exp(NEG - NEG) = 1 on fully-masked rows; zero it
    corr = jnp.exp(m - m_new)
    l = l * corr + jnp.sum(p, axis=-1)
    o = o * corr[..., None] + jnp.einsum(
        "hqk,hkd->hqd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return o, m_new, l


def _ring_attention_local(q, k, v, *, axis: str, causal: bool,
                          layout: str = "contiguous"):
    """Per-shard body (inside ``shard_map``): rotate K/V around the ring.

    Each of the ``p`` hops computes one (n_local x n_local) score block
    (its live quarter-blocks under the causal-zigzag layout) and folds it
    into the online softmax; K/V then move one hop forward — the
    attention analogue of the ghost-row ``ppermute`` at
    ``parallel/halo.py:halo_pad_y`` (reference: ``3-life/life_mpi.c:203-207``).

    Differentiation takes the ring flash backward (``_ring_flash``'s
    ``custom_vjp``): the forward saves only ``(q, k, v, o, logsumexp)``
    per shard — O(seq·d/p) — and the backward re-rotates K/V around the
    ring, recomputing each block from the saved row statistics.
    """
    p = axis_size(axis)
    if p == 1:
        # A 1-device ring is just full local attention (under EITHER
        # layout: the p=1 zigzag order is the identity); the
        # doubly-chunked local path additionally skips future k blocks
        # under causal. GQA folds query groups on the jnp engine; on
        # TPU, budget-fitting GQA expands K/V into the Pallas kernel
        # instead (_flash_dispatch_plan).
        return _attention_chunked(q, k, v, causal)
    return _ring_flash(axis, causal, layout, q, k, v)


def _ring_forward(axis: str, causal: bool, layout: str, q, k, v):
    """The rotate-and-fold forward; returns the normalised output and the
    per-row logsumexp ``L = m + log l`` of the scaled scores in the FOLDED
    GQA layout ``(hkv, n_local·g)`` — the one statistic the ring backward
    needs to recompute any hop's probabilities as ``exp(s - L)``.

    ``layout`` picks the token-to-shard map (:func:`_ring_positions`):
    every position the masks see flows from it. Causal-zigzag hops run
    HALF-blocks (live-pair table in the zigzag branch below): per hop
    each device computes only its live (q-half x k-half) pairs — two
    quarter-size blocks off the diagonal, three (two of them
    half-masked) on the src == idx hop — so a causal trip costs every
    device about half a full-block per hop, versus the contiguous split
    where hop wall-clock is set by whichever device's block is
    unskipped (the straggler)."""
    p = axis_size(axis)
    # TPU-eligible hop shapes take the per-hop Pallas engine instead of
    # the jnp fold below (which remains the oracle and the fallback) —
    # same ring schedule, flash-kernel hops, online-softmax merge.
    hop_plan = _ring_hop_plan(q, k, v, causal, layout)
    if hop_plan is not None:
        if causal and layout == "zigzag":
            return _ring_forward_hopflash_zz(axis, p, q, k, v, hop_plan)
        return _ring_forward_hopflash(axis, causal, p, q, k, v, hop_plan)
    # Non-causal folds build no masks, so every consumer of the axis
    # index is dead code — and jax 0.4.37's shard_map does not DCE the
    # resulting bare partition_id, which the SPMD partitioner then
    # rejects. Only materialise the index when a mask can consume it.
    idx = lax.axis_index(axis) if causal else 0
    nl, d = q.shape[1:]
    hkv = k.shape[0]
    g = q.shape[0] // hkv
    # GQA stays un-expanded through the whole ring: K/V blocks ride the
    # ppermutes at hkv heads and the folds run q with query groups
    # folded into the row axis (row r <-> position r // g), exactly like
    # the local flash path — no repeated K/V is ever materialised.
    q32 = _fold_groups(q.astype(jnp.float32), hkv, g)
    perm = ring_perm(p, 1)
    cg = _Q_CHUNK * g
    zz = causal and layout == "zigzag"

    def make_folder(npos, qsub, qpos_of):
        """(state0, fold, finish) for a q subset of ``npos`` positions
        (folded rows ``npos*g``). Flash-style q chunking whenever the
        subset is long: q rows are independent, so pad them to a chunk
        multiple (padded rows compute junk that ``finish`` slices off)
        — no divisibility cliff. ``qpos_of`` maps subset-local position
        indices to global token positions."""
        chunked = npos > _Q_CHUNK
        nc = -(-npos // _Q_CHUNK)
        npp = nc * _Q_CHUNK if chunked else npos
        if npp != npos:
            qsub = jnp.pad(qsub, ((0, 0), (0, (npp - npos) * g), (0, 0)))
        rows = npp * g
        state0 = (jnp.zeros((hkv, rows, d), jnp.float32),
                  jnp.full((hkv, rows), _NEG, jnp.float32),
                  jnp.zeros((hkv, rows), jnp.float32))

        def fold(state, kb, vb, kpos):
            o, m, l = state
            if not chunked:
                qpos = qpos_of(jnp.arange(npos * g) // g)
                return _block_update(qsub, kb, vb, qpos, kpos, None,
                                     causal, o, m, l)
            # Scan q (and its running state) in (hkv, _Q_CHUNK * g)
            # folded slices so only a (hkv, _Q_CHUNK * g, nk) score
            # block is ever live.

            def body(_, xs):
                qc, oc, mc, lc, ci = xs
                qpos = qpos_of(ci * _Q_CHUNK + jnp.arange(cg) // g)
                oc, mc, lc = _block_update(qc, kb, vb, qpos, kpos, None,
                                           causal, oc, mc, lc)
                return None, (oc, mc, lc)

            _, (os_, ms, ls) = lax.scan(
                body, None,
                (_chunk(qsub, nc, cg), _chunk(o, nc, cg),
                 _chunk(m, nc, cg), _chunk(l, nc, cg), jnp.arange(nc)))
            return _unchunk(os_), _unchunk(ms), _unchunk(ls)

        def finish(state):
            return tuple(x[:, : npos * g] for x in state)

        return state0, fold, finish

    if not zz:
        state0, fold_q, finish = make_folder(
            nl, q32, lambda r: _ring_positions(layout, idx, p, nl, r))

        def fold(j, state, kb, vb):
            # After j forward rotations my K/V block originated on ring
            # position (idx - j) mod p.
            src = (idx - j) % p
            kpos = _ring_positions(layout, src, p, nl, jnp.arange(nl))
            if not causal:
                return fold_q(state, kb, vb, kpos)
            # Contiguous causal: blocks entirely in the future
            # (src > idx) contribute nothing; skip their matmul+exp
            # instead of computing and masking it out (~(p-1)/2 of the
            # hops on average). The predicate differs per device
            # (idx-dependent), so neither branch may contain a
            # collective — the ppermutes stay outside, in the hop body.
            # cond is reverse-mode differentiable; the scan lowering is
            # unaffected.
            return lax.cond(
                src <= idx,
                lambda s: fold_q(s, kb, vb, kpos),
                lambda s: s,
                state)
    else:
        # Causal zigzag: shard idx holds half-chunks (idx, 2p-1-idx) of
        # size half = nl/2. Of the four (q-half x k-half) pairs per hop
        # only these ever carry unmasked work (`_zz_pairs`):
        #   (lo, lo)  iff src <= idx   (diagonal at src == idx)
        #   (hi, lo)  always           (high chunks are after every low)
        #   (hi, hi)  iff src >= idx   (diagonal at src == idx)
        # — (lo, hi) is always fully masked. That is two quarter-blocks
        # per off-diagonal hop (three on the diagonal hop, two of them
        # half-masked) on EVERY device: balanced, and about half the
        # FLOPs of a masked full block.
        half = nl // 2
        hg = half * g
        s_lo0, fold_lo, fin_lo = make_folder(
            half, q32[:, :hg], lambda r: idx * half + r)
        s_hi0, fold_hi, fin_hi = make_folder(
            half, q32[:, hg:], lambda r: (2 * p - 1 - idx) * half + r)

        def fold(j, state, kb, vb):
            s_lo, s_hi = state
            src = (idx - j) % p
            k_lo, k_hi = kb[:, :half], kb[:, half:]
            v_lo, v_hi = vb[:, :half], vb[:, half:]
            kpos_lo = src * half + jnp.arange(half)
            kpos_hi = (2 * p - 1 - src) * half + jnp.arange(half)
            s_lo = lax.cond(
                src <= idx,
                lambda s: fold_lo(s, k_lo, v_lo, kpos_lo),
                lambda s: s, s_lo)
            s_hi = fold_hi(s_hi, k_lo, v_lo, kpos_lo)
            s_hi = lax.cond(
                src >= idx,
                lambda s: fold_hi(s, k_hi, v_hi, kpos_hi),
                lambda s: s, s_hi)
            return s_lo, s_hi

        state0 = (s_lo0, s_hi0)

    # Chaos hook (robust.chaos): a planned nan_hop/inf_hop poisons the
    # K/V partials of exactly that hop, baked in at trace time. The jnp
    # fold carries the same injection point as the per-hop Pallas engine
    # so an UNgated fold provably diverges under injection; the guarded
    # recovery path re-traces under chaos.suppressed() and stays clean.
    # When MOMP_CHAOS is unset no ops are added (trace-time `is None`).
    from mpi_and_open_mp_tpu.robust import chaos as _chaos

    _poison = _chaos.hop_poison_spec()
    if _poison is not None:
        fold = _chaos.poisoned_fold(fold, _poison)

    def hop(j, carry):
        state, kb, vb = carry
        # Double-buffered rotation: issue the NEXT hop's K/V transfer
        # before folding the block just received, so the async
        # collective-permute rides the fabric while the MXU computes the
        # score block (XLA's latency-hiding scheduler pairs the
        # permute-start here with a permute-done after the fold — the
        # fold reads only the held kb/vb, never the in-flight pair). The
        # ppermutes stay unconditional and outside fold's causal `cond`:
        # collectives inside a per-device branch would deadlock the ring.
        kb_next = lax.ppermute(kb, axis, perm)
        vb_next = lax.ppermute(vb, axis, perm)
        state = fold(j, state, kb, vb)
        return state, kb_next, vb_next

    # p-1 rotate+compute hops, then a final fold with no trailing rotation
    # (the p-th ppermute pair would only feed discarded loop carries).
    state, kb, vb = lax.fori_loop(0, p - 1, hop, (state0, k, v))
    state = fold(p - 1, state, kb, vb)
    if zz:
        o, m, l = (jnp.concatenate(parts, axis=1) for parts in zip(
            fin_lo(state[0]), fin_hi(state[1])))
    else:
        o, m, l = finish(state)
    L = jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-37)), -_NEG)
    o = o / jnp.where(l > 0, l, 1.0)[..., None]
    return _unfold_groups(o, hkv, g).astype(q.dtype), L


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _ring_flash(axis: str, causal: bool, layout: str, q, k, v):
    return _ring_forward(axis, causal, layout, q, k, v)[0]


def _ring_flash_fwd(axis: str, causal: bool, layout: str, q, k, v):
    o, L = _ring_forward(axis, causal, layout, q, k, v)
    return o, (q, k, v, o, L)


def _flash_block_grads(qc, doc, Lc, Dc, kb, vb, mask, scale: float):
    """One block of the flash backward — THE shared arithmetic of the
    chunked (``_flash_chunked_bwd``) and ring (``_ring_flash_bwd``)
    backwards, so the two paths cannot drift numerically:

        p  = exp(s - L)            (recomputed; ``mask`` = allow or None)
        dv = pᵀ do ;  t = p∘(do vᵀ - D)
        dq = scale · t k ;  dk = scale · tᵀ q

    All operands float32. Folded GQA q rows carry all g groups: the
    dk/dv einsums sum the group contributions into the hkv kv heads.
    Returns ``(dq, dk, dv)`` for the block.
    """
    f32 = jnp.float32
    s = jnp.einsum("hqd,hkd->hqk", qc, kb,
                   preferred_element_type=f32) * scale
    p = jnp.exp(s - Lc[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    dp = jnp.einsum("hqd,hkd->hqk", doc, vb, preferred_element_type=f32)
    t = p * (dp - Dc[..., None])
    return (
        scale * jnp.einsum("hqk,hkd->hqd", t, kb,
                           preferred_element_type=f32),
        scale * jnp.einsum("hqk,hqd->hkd", t, qc,
                           preferred_element_type=f32),
        jnp.einsum("hqk,hqd->hkd", p, doc, preferred_element_type=f32),
    )


def _ring_flash_bwd(axis: str, causal: bool, layout: str, res, do):
    """Ring flash backward: O(seq·d/p) residuals on the sharded path.

    K/V blocks make a second trip around the ring, each carrying its own
    ``(dk, dv)`` accumulator: at every hop the local device recomputes the
    block's probabilities from the saved logsumexp (``p = exp(s - L)``),
    folds the block's contribution into its local ``dq`` and into the
    travelling accumulators, and forwards all four. After ``p`` rotations
    the accumulators are back on their home shard having collected every
    device's contribution — the gradient analogue of the forward's
    rotate-and-fold, same ``ppermute`` fabric, no gather. Per block the
    arithmetic matches ``_flash_chunked_bwd``:

        p  = exp(s - L)            (recomputed, causal-masked)
        D  = rowsum(do * o)
        dv += pᵀ do ;  t = p∘(do vᵀ - D)
        dq += scale · t k ;  dk += scale · tᵀ q

    Causal hop skipping mirrors the forward (blocks with src > idx are
    never computed); the ``ppermute``s stay unconditional and outside the
    per-device ``cond`` — a collective inside a branch would deadlock the
    ring. GQA runs in the same folded layout as the forward: ``dk``/``dv``
    come out group-summed, ``dq`` is unfolded at the end.
    """
    q, k, v, o, L = res
    p = axis_size(axis)
    # TPU-eligible hop shapes take the per-hop Pallas backward kernels
    # instead of the jnp fold below (which remains the oracle and the
    # ineligible-shape fallback) — same travelling-dk/dv schedule,
    # kernel-rate per-hop block gradients.
    bwd_plan = _ring_hop_bwd_plan(q, k, v, causal, layout)
    if bwd_plan is not None:
        return _ring_backward_hopflash(axis, causal, p, res, do, bwd_plan)
    # See the forward's note: keep the axis index out of the non-causal
    # trace (its consumers are all dead there and 0.4.37's shard_map
    # leaves the bare partition_id for the SPMD partitioner to reject).
    idx = lax.axis_index(axis) if causal else 0
    nl, d = q.shape[1:]
    hkv = k.shape[0]
    g = q.shape[0] // hkv
    scale = 1.0 / math.sqrt(d)
    f32 = jnp.float32
    perm = ring_perm(p, 1)

    q32 = _fold_groups(q.astype(f32), hkv, g)
    do32 = _fold_groups(do.astype(f32), hkv, g)
    o32 = _fold_groups(o.astype(f32), hkv, g)
    D = jnp.sum(do32 * o32, axis=-1)  # (hkv, nl*g)
    Lf = L

    cg = _Q_CHUNK * g
    zz = causal and layout == "zigzag"

    def block_grads(qc, doc, Lc, Dc, qpos, kpos, kb32, vb32):
        mask = _mask_from_pos(qpos, kpos, None, causal)
        return _flash_block_grads(qc, doc, Lc, Dc, kb32, vb32, mask, scale)

    def make_bwd(npos, qsub, dosub, Lsub, Dsub, qpos_of):
        """Per-hop (dq, dk, dv) contribution fn for a q subset of
        ``npos`` positions against one K/V block — the same q-chunking
        decision as the forward's folder; padded rows carry L = -_NEG
        (huge) so their recomputed p underflows to 0 — they contribute
        nothing to dk/dv and their dq rows are sliced off."""
        chunked = npos > _Q_CHUNK
        nc = -(-npos // _Q_CHUNK)
        npp = nc * _Q_CHUNK if chunked else npos
        if npp != npos:
            rows = (npp - npos) * g
            qsub = jnp.pad(qsub, ((0, 0), (0, rows), (0, 0)))
            dosub = jnp.pad(dosub, ((0, 0), (0, rows), (0, 0)))
            Dsub = jnp.pad(Dsub, ((0, 0), (0, rows)))
            Lsub = jnp.pad(Lsub, ((0, 0), (0, rows)),
                           constant_values=-_NEG)

        def contribution(kb32, vb32, kpos):
            if not chunked:
                qpos = qpos_of(jnp.arange(npos * g) // g)
                dqs, dkj, dvj = block_grads(qsub, dosub, Lsub, Dsub,
                                            qpos, kpos, kb32, vb32)
                return dqs, dkj, dvj

            def body(carry, xs):
                dka, dva = carry
                qc, doc, Lc, Dc, ci = xs
                qpos = qpos_of(ci * _Q_CHUNK + jnp.arange(cg) // g)
                dqc, dkc, dvc = block_grads(qc, doc, Lc, Dc, qpos, kpos,
                                            kb32, vb32)
                return (dka + dkc, dva + dvc), dqc

            z = jnp.zeros((hkv, kb32.shape[1], d), f32)
            (dkj, dvj), dqs = lax.scan(
                body, (z, z),
                (_chunk(qsub, nc, cg), _chunk(dosub, nc, cg),
                 _chunk(Lsub, nc, cg), _chunk(Dsub, nc, cg),
                 jnp.arange(nc)))
            return _unchunk(dqs)[:, : npos * g], dkj, dvj

        return contribution

    if not zz:
        contrib_q = make_bwd(
            nl, q32, do32, Lf, D,
            lambda r: _ring_positions(layout, idx, p, nl, r))

        def contribute(j, kb, vb):
            src = (idx - j) % p
            kpos = _ring_positions(layout, src, p, nl, jnp.arange(nl))
            if not causal:
                return contrib_q(kb.astype(f32), vb.astype(f32), kpos)
            # Hop skipping mirrors the forward (contiguous causal). The
            # f32 casts live INSIDE the taken branch: as cond operands
            # XLA would materialise them on skipped hops too.
            return lax.cond(
                src <= idx,
                lambda _: contrib_q(kb.astype(f32), vb.astype(f32), kpos),
                lambda _: (jnp.zeros((hkv, nl * g, d), f32),
                           jnp.zeros((hkv, nl, d), f32),
                           jnp.zeros((hkv, nl, d), f32)),
                None)
    else:
        # Same live-pair analysis as the forward's causal-zigzag fold:
        # (lo,lo) iff src <= idx; (hi,lo) always; (hi,hi) iff
        # src >= idx; (lo,hi) never — two quarter-blocks of gradient
        # work per off-diagonal hop (three on the diagonal hop),
        # uniformly across devices.
        half = nl // 2
        hg = half * g
        bwd_lo = make_bwd(half, q32[:, :hg], do32[:, :hg], Lf[:, :hg],
                          D[:, :hg], lambda r: idx * half + r)
        bwd_hi = make_bwd(half, q32[:, hg:], do32[:, hg:], Lf[:, hg:],
                          D[:, hg:], lambda r: (2 * p - 1 - idx) * half + r)

        def contribute(j, kb, vb):
            src = (idx - j) % p
            k_lo, k_hi = kb[:, :half], kb[:, half:]
            v_lo, v_hi = vb[:, :half], vb[:, half:]
            kpos_lo = src * half + jnp.arange(half)
            kpos_hi = (2 * p - 1 - src) * half + jnp.arange(half)

            def zero3(_):
                return (jnp.zeros((hkv, hg, d), f32),
                        jnp.zeros((hkv, half, d), f32),
                        jnp.zeros((hkv, half, d), f32))

            # f32 casts inside each taken branch (see the contiguous
            # note); the always-live (hi, lo) pair casts unconditionally.
            dq_lo, dk_lo, dv_lo = lax.cond(
                src <= idx,
                lambda _: bwd_lo(k_lo.astype(f32), v_lo.astype(f32),
                                 kpos_lo), zero3, None)
            dq_hi, dk_lo2, dv_lo2 = bwd_hi(k_lo.astype(f32),
                                           v_lo.astype(f32), kpos_lo)
            dq_hi2, dk_hi, dv_hi = lax.cond(
                src >= idx,
                lambda _: bwd_hi(k_hi.astype(f32), v_hi.astype(f32),
                                 kpos_hi), zero3, None)
            return (jnp.concatenate([dq_lo, dq_hi + dq_hi2], axis=1),
                    jnp.concatenate([dk_lo + dk_lo2, dk_hi], axis=1),
                    jnp.concatenate([dv_lo + dv_lo2, dv_hi], axis=1))

    def hop(j, carry):
        dq, kb, vb, dkb, dvb = carry
        # Prefetch the next K/V pair before the fold (the forward's
        # double-buffering); the accumulator permutes necessarily wait
        # on the fold's contribution.
        kb_next = lax.ppermute(kb, axis, perm)
        vb_next = lax.ppermute(vb, axis, perm)
        dqj, dkj, dvj = contribute(j, kb, vb)
        dkb = lax.ppermute(dkb + dkj, axis, perm)
        dvb = lax.ppermute(dvb + dvj, axis, perm)
        return dq + dqj, kb_next, vb_next, dkb, dvb

    z = jnp.zeros((hkv, nl, d), f32)
    dq, kb, vb, dkb, dvb = lax.fori_loop(
        0, p - 1, hop, (jnp.zeros((hkv, nl * g, d), f32), k, v, z, z))
    # Last block: contribute, then one final accumulator rotation (the
    # p-th) lands every (dk, dv) back on its home shard; kb/vb need no
    # trailing transfer.
    dqj, dkj, dvj = contribute(p - 1, kb, vb)
    dq = dq + dqj
    dk = lax.ppermute(dkb + dkj, axis, perm)
    dv = lax.ppermute(dvb + dvj, axis, perm)
    dq = _unfold_groups(dq, hkv, g).astype(q.dtype)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


# On-TPU the single-device engine can dispatch to jax's bundled Pallas
# flash-attention kernel (block-pipelined HBM->VMEM, MXU-shaped tiles)
# instead of the jnp-chunked path, which tops out around 25% MFU as pure
# XLA. The kernel is only faster with EXPLICIT block sizes: chip
# head-to-head (v5 lite, 8 heads, d=128, causal bf16, chain-differenced)
# measured the kernel's own default blocks at 15-17 TFLOP/s forward —
# SLOWER than the 47-49 jnp engine — while uniform 512/1024 blocks reach
# 105-140 forward and 84-120 full-grad TFLOP/s (the jnp flash backward
# runs ~32). 2048 blocks fail to compile (VMEM). Dispatch therefore
# always passes explicit blocks (:func:`_flash_block_for`). The jnp
# path remains the CPU/interpret oracle and the fallback for shapes the
# kernel doesn't take. MOMP_TPU_FLASH=0 forces the jnp engine
# everywhere (and the sweep's parity gate flips this off at runtime if
# the kernel ever disagrees with the dense oracle).
_TPU_FLASH = os.environ.get("MOMP_TPU_FLASH", "1") != "0"

# MOMP_PALLAS_INTERPRET=1 routes Pallas-eligible shapes through the
# bundled kernel in Pallas interpret mode on ANY backend — the CPU-mesh
# test rig for kernel-inside-shard_map paths (tests/conftest.py pins 8
# virtual CPU devices; nothing here needs hardware). Interpret
# eligibility is narrower than the chip's: jax 0.4.37's interpret-mode
# discharge rule breaks on the kernel's scratch branch (block_k <
# kv_seq) and on the kernel's own backward, so only block == seq
# forwards qualify — exactly what the per-hop ring engine runs (our own
# custom_vjp supplies the ring backward; the kernel's vjp is never
# entered there).
_PALLAS_INTERPRET = os.environ.get("MOMP_PALLAS_INTERPRET", "0") == "1"


@contextlib.contextmanager
def _pallas_interpret_calls(fa):
    """Trace-time patch turning every ``pallas_call`` the bundled kernel
    makes into an interpret-mode call (jax 0.4.37 has no global
    interpret switch). A no-op unless ``_PALLAS_INTERPRET`` is set.
    Callers flipping the flag at runtime must ``jax.clear_caches()`` —
    the flag is not a jit cache key."""
    if not _PALLAS_INTERPRET:
        yield
        return
    orig = fa.pl.pallas_call
    fa.pl.pallas_call = functools.partial(orig, interpret=True)
    try:
        yield
    finally:
        fa.pl.pallas_call = orig

# Chip-validated uniform block edges, best first; the auto dispatch
# picks the largest that divides the sequence AND leaves at least
# _MIN_GRID programs per grid axis (gate + recorders then exercise that
# very configuration).
_AUTO_BLOCKS = (1024, 512, 256, 128)

# Grid-occupancy floor for the auto block choice. Chip-measured at 8k
# causal bf16 (8 heads, d=128): b=1024 leaves an 8x8 grid and the
# kernel's vjp collapses to 25.8 TFLOP/s grad (79.5 fwd); b=512 (16x16)
# measures 113.4 grad / 97.9 fwd — the backward needs >= ~16 programs
# per axis to fill the chip's pipeline. 16k+ at b=1024 already satisfy
# the floor (137-147 fwd measured). The floor applies at EVERY edge:
# 2k-4k sequences step down to 128/256 blocks for a full grid rather
# than keep the largest-dividing block with a starved 2-4 program grid
# (the 8k collapse extrapolated per-edge; the per-hop ring engine puts
# exactly these short local blocks on the kernel, so starved grids are
# no longer a corner case). A sequence too short to satisfy the floor
# with ANY edge (< 2048) takes the largest fitting block — at that size
# the kernel call is latency- not occupancy-bound.
_MIN_GRID = 16


def tpu_flash_engine() -> str:
    """Which engine ``flash_attention`` will dispatch eligible shapes to
    — ``"pallas"`` or ``"jnp"`` — for recorders' provenance fields.
    Off-TPU the answer is always ``"jnp"`` regardless of the flag."""
    try:
        on_tpu = jax.default_backend() == "tpu"
    except RuntimeError:
        on_tpu = False
    return "pallas" if (_TPU_FLASH and on_tpu) else "jnp"


def _fold_batch(x: jnp.ndarray) -> jnp.ndarray:
    """Fold a (B, h, n, d) request batch into the head axis: (B*h, n, d).

    Heads are UNSHARDED in every sequence-parallel spec here
    (``_seq_spec`` keeps axis 0 replicated), so a request batch rides
    the fold/kernel machinery unchanged as extra heads — including GQA:
    with g = H/Hkv query groups, folded q head ``b*H + h`` integer-
    divides by g to kv head ``b*Hkv + h//g``, i.e. exactly board ``b``'s
    own kv heads. Ring ``ppermute`` payloads become (B*Hkv, n_local, d)
    — one hop moves every request's K/V block."""
    return x.reshape((x.shape[0] * x.shape[1],) + tuple(x.shape[2:]))


def _fold_batch_probes(q, k, v):
    """ShapeDtypeStruct twins of :func:`_fold_batch` over (q, k, v) —
    engine-stamp functions probe shapes without touching data."""
    return tuple(
        jax.ShapeDtypeStruct(
            (x.shape[0] * x.shape[1],) + tuple(x.shape[2:]), x.dtype)
        for x in (q, k, v))


def flash_engine_for(q, k, v) -> str:
    """Shape-aware engine provenance: the engine ``flash_attention``
    will actually dispatch THESE operands to, with the effective block
    edge (``"pallas:b512"``) since perf swings ~8x across blocks.
    Recorders must stamp artifacts with this (not the flag-level
    :func:`tpu_flash_engine`): a block override that doesn't divide a
    timed sequence routes that shape to the jnp engine regardless of
    the flag. Sequences at or below the chunk size short-circuit to the
    dense reference before any engine dispatch and stamp ``"dense"``.

    4D ``(B, heads, seq, d)`` operands (the request-batched entry) fold
    the batch into the head axis exactly as ``flash_attention`` does,
    and the stamp gains a ``:b{B}`` suffix so recorded artifacts carry
    the batching alongside the block edge. Works on
    ``jax.ShapeDtypeStruct`` probes like the 3D form."""
    if len(q.shape) == 4:
        probe_q, probe_k, probe_v = _fold_batch_probes(q, k, v)
        return flash_engine_for(probe_q, probe_k, probe_v) + f":b{q.shape[0]}"
    if q.shape[1] <= _Q_CHUNK:  # mirrors _attention_chunked's ordering
        return "dense"
    plan = _flash_dispatch_plan(q, k, v)
    if plan is None:
        return "jnp"
    return _plan_stamp(plan)


def disable_tpu_flash() -> None:
    """Force the jnp engine from here on (recorders call this when the
    Pallas kernel fails a parity gate or fails to compile). Drops jit
    caches too: already-compiled callers would otherwise keep
    dispatching to the Pallas kernel, making the flip silently a no-op.
    """
    global _TPU_FLASH
    _TPU_FLASH = False
    jax.clear_caches()


def gated_parity_check(heads: int = 8, n: int = 2048, dim: int = 128,
                       seed: int = 0, for_seq: int | None = None,
                       kv_heads: int | None = None,
                       ) -> tuple[bool, str, list[str]]:
    """THE honesty gate every attention recorder runs before recording:
    check whatever engine :func:`flash_attention` dispatches to against
    the dense oracle — FORWARD AND FULL (q, k, v) GRADIENTS, since the
    recorders publish backward timings and the Pallas kernel brings its
    own custom_vjp that only this gate ever checks on chip — at f32,
    highest matmul precision (the default TPU f32 matmul takes bf16 MXU
    passes whose rounding would swamp the algorithmic tolerance); on a
    Pallas-engine failure (numeric or compile),
    :func:`disable_tpu_flash` and re-gate the jnp engine.

    ``for_seq`` aims the gate at the exact engine+block configuration a
    length-``for_seq`` dispatch will use (the dense oracle is O(n²), so
    the gate cannot simply run at the timed length): a Pallas-bound
    sequence pins its effective block for the gate's smaller run, and a
    jnp-bound one steers the gate sequence off the 128-multiple grid so
    the gate dispatches the jnp engine too. ``kv_heads`` gates a
    GQA/MQA configuration (fewer K/V heads): the gate operands carry it,
    so a timed GQA shape's engine — the expand dispatch, or folded jnp —
    is what gets checked; the ``for_seq`` routing probe uses bf16
    operands (what recorders time), since the expand budget is
    byte-counted. Recorders timing several sequences must gate once per
    distinct configuration (``_flash_block_for(seq, dim)`` x kv_heads).

    Returns ``(ok, engine, notes)`` — ``engine`` is the engine the gate
    passed on (= the one subsequent calls will use), ``notes`` records
    any per-engine failure on the way. Callers decide abort-vs-continue
    policy; the gate itself is shared so recorders cannot drift.
    """
    import numpy as np

    global _FORCED_BLOCK, _FORCED_BLOCK_BWD
    hkv = kv_heads or heads
    forced = 0
    forced_bwd = 0
    steer_jnp = False
    if for_seq is not None and tpu_flash_engine() == "pallas":
        # Route exactly as the timed shape will: same plan function,
        # bf16 shape probes (recorders time bf16; the expand budget is
        # byte-counted so dtype matters).
        sq = jax.ShapeDtypeStruct((heads, for_seq, dim), jnp.bfloat16)
        skv = jax.ShapeDtypeStruct((hkv, for_seq, dim), jnp.bfloat16)
        plan = (_flash_dispatch_plan(sq, skv, skv)
                if for_seq > _Q_CHUNK else None)
        if plan is not None:
            forced, forced_bwd = plan[1], plan[2]
        else:
            # The timed shape is jnp-bound (no block divides it, an
            # override doesn't, or its GQA expansion is over budget):
            # steer the gate sequence off the block grid so the gate
            # dispatches the jnp engine too.
            steer_jnp = True
            if n % 128 == 0:
                n += 16

    # The gate must exercise the same engine+block the timed shapes will
    # get: under a pin (MOMP_FLASH_BLOCK override, which wins, or the
    # for_seq force above), round the gate sequence up to a block
    # multiple so the Pallas kernel with those very block sizes is what
    # gets checked — otherwise an oversized block would make the gate
    # silently jnp-only while the recordings dispatch ungated. (Not
    # when steering jnp-ward: the round-up would put an overridden
    # block's multiple right back on the Pallas grid.)
    blk = _flash_block_override() or forced
    bwd = _flash_block_override_bwd() or forced_bwd or blk
    if blk and not steer_jnp:
        # With the backward edge decoupled, the gate sequence must be a
        # multiple of BOTH effective edges (the kernel rejects either
        # non-divisor), so round up to their lcm.
        m = math.lcm(blk, bwd)
        n = -(-n // m) * m
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((heads, n, dim)), jnp.float32)
    k, v = (jnp.asarray(rng.standard_normal((hkv, n, dim)), jnp.float32)
            for _ in range(2))

    def close(a, b, tol):
        return bool(np.allclose(np.asarray(a), np.asarray(b),
                                rtol=tol, atol=tol))

    def oracle(a, b, c):
        # The dense oracle wants equal heads; expanding INSIDE the
        # differentiated function keeps the reference dk/dv group-summed
        # to the same (hkv, ...) shapes the gated engine produces.
        return attention_reference(
            a, *_repeat_heads(b, c, heads // hkv), causal=True)

    def gate() -> bool:
        with jax.default_matmul_precision("highest"):
            got = flash_attention(q, k, v, causal=True)
            want = oracle(q, k, v)
            if not close(got, want, 2e-4):
                return False
            g_got = jax.grad(
                lambda a, b, c: jnp.sum(
                    flash_attention(a, b, c, causal=True) ** 2),
                argnums=(0, 1, 2))(q, k, v)
            g_want = jax.grad(
                lambda a, b, c: jnp.sum(oracle(a, b, c) ** 2),
                argnums=(0, 1, 2))(q, k, v)
        return all(close(a, b, 5e-4) for a, b in zip(g_got, g_want))

    notes: list[str] = []

    def attempt() -> bool:
        try:
            ok = gate()
        except Exception as e:
            notes.append(f"{tpu_flash_engine()} engine: "
                         f"{type(e).__name__}: {e}"[:160])
            return False
        if not ok:
            notes.append(f"{tpu_flash_engine()} engine failed parity")
        return ok

    # Retry keyed on the engine the first attempt actually dispatched to
    # (not the bare flag): off-TPU a jnp failure would otherwise trigger
    # a pointless cache drop and an identical second jnp run. The ladder
    # itself is robust.guards.with_fallback — the same engine-ranked
    # retry ring_attention's hop guard uses; attempt() keeps appending
    # its own notes, and disable_tpu_flash flips the global so the
    # post-fallback tpu_flash_engine() reports the engine that passed.
    from mpi_and_open_mp_tpu.robust.guards import (
        FallbackExhausted, with_fallback)

    _FORCED_BLOCK = forced
    _FORCED_BLOCK_BWD = forced_bwd
    try:
        engines = [(tpu_flash_engine(), attempt)]
        if tpu_flash_engine() == "pallas" and not steer_jnp:
            engines.append(
                ("jnp", lambda: (disable_tpu_flash(), attempt())[1]))
        try:
            with_fallback(engines, validator=bool)
            ok = True
        except FallbackExhausted:
            ok = False
    finally:
        _FORCED_BLOCK = 0
        _FORCED_BLOCK_BWD = 0
    # When the steer aimed the gate at the jnp engine, that IS the
    # engine the for_seq shape will use — report it, not the flag.
    return ok, ("jnp" if steer_jnp else tpu_flash_engine()), notes


def _parse_block_env(name: str) -> int:
    """Validated block-edge env knob (0 = unset). One shared parse for
    the routing predicate, the dispatch, and the parity gate, so they
    cannot disagree on the effective block — and a typo'd knob fails
    loudly with its own name, not as an opaque error from some later
    dispatch."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return 0
    try:
        b = int(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r} is not an integer") from None
    if b < 0 or (b and (b < 128 or b % 128)):
        raise ValueError(
            f"{name}={b} must be 0 or a multiple of 128 >= 128")
    return b


def _flash_block_override() -> int:
    """The ``MOMP_FLASH_BLOCK`` pin: all blocks (forward, and backward
    too unless the backward knob overrides it)."""
    return _parse_block_env("MOMP_FLASH_BLOCK")


def _flash_block_override_bwd() -> int:
    """The ``MOMP_FLASH_BLOCK_BWD`` pin: the eight dq/dkv blocks only
    (:func:`_flash_bwd_block_for`)."""
    return _parse_block_env("MOMP_FLASH_BLOCK_BWD")


# Gate-time pins of the auto block choices (module-internal; see
# gated_parity_check): let the small-sequence parity gate run the very
# block configuration a larger timed sequence will dispatch, since the
# dense oracle is O(n^2) and cannot be evaluated at the timed length.
# The backward edge is pinned separately (decoupled dispatch).
_FORCED_BLOCK = 0
_FORCED_BLOCK_BWD = 0

# b*d budget for the auto choice, anchored at the chip-validated
# (b=1024, d=128) point: 2048*128 failed to compile (VMEM), so wider
# head dims scale the block edge down rather than risk an unvalidated
# footprint on library callers with no fallback path.
_BLOCK_BUDGET = 1024 * 128


def _block_pin() -> int:
    """The pinned block edge, if any: the ``MOMP_FLASH_BLOCK`` env
    override, else the gate's module-internal force."""
    return _flash_block_override() or _FORCED_BLOCK


def _flash_block_for(n: int, d: int = 128) -> int:
    """Effective Pallas FORWARD block edge for a ``(seq=n, head_dim=d)``
    dispatch: the pin (env override / gate force) if set, else the
    largest chip-validated block (``_AUTO_BLOCKS``) dividing ``n``
    within the ``b*d <= _BLOCK_BUDGET`` footprint that keeps the grid
    at least ``_MIN_GRID`` programs per axis (short sequences starve
    the kernel below that — see the ``_MIN_GRID`` note); if no edge
    satisfies the floor, the largest fitting block regardless. 0 = no
    block fits (the shape is then jnp-engine territory)."""
    b = _block_pin()
    if b:
        return b
    fits = [b for b in _AUTO_BLOCKS
            if b * d <= _BLOCK_BUDGET and n % b == 0]
    for b in fits:
        if n >= _MIN_GRID * b:
            return b
    return fits[0] if fits else 0


def _flash_bwd_block_for(n: int, d: int = 128) -> int:
    """Effective Pallas BACKWARD block edge (the eight dq/dkv blocks).
    Decoupled from the forward's: ``MOMP_FLASH_BLOCK_BWD`` (or the
    gate's backward force) pins it independently, so a chip session can
    sweep e.g. a b1024 forward against a b512 backward — the backward
    is the grid-occupancy-sensitive side (``_MIN_GRID`` note) and its
    best edge need not match the forward's. Unpinned, it follows the
    forward choice (a single ``MOMP_FLASH_BLOCK`` still pins all eight
    blocks, exactly the pre-decoupling behaviour); the auto edges
    coincide until a chip sweep separates them."""
    b = _flash_block_override_bwd() or _FORCED_BLOCK_BWD
    if b:
        return b
    return _flash_block_for(n, d)


def _pallas_flash_eligible(q, k, v) -> bool:
    """Static (trace-time) routing predicate for the bundled Pallas TPU
    kernel taking the operands DIRECTLY: TPU backend (or interpret mode
    on any backend), equal head counts (GQA shapes go through
    :func:`_flash_dispatch_plan`'s expand form instead), validated
    forward AND backward block edges that divide the sequence within
    the ``b*d`` footprint budget (:func:`_flash_block_for` /
    :func:`_flash_bwd_block_for`; a pinned block tightens divisibility
    to its own multiple), MXU-width head dim, and a dtype the MXU takes
    directly. Interpret mode additionally requires block == seq (jax
    0.4.37's interpret discharge rule breaks on the scratch branch)."""
    if not _TPU_FLASH:
        return False
    if not _PALLAS_INTERPRET:
        try:
            if jax.default_backend() != "tpu":
                return False
        except RuntimeError:  # no backend at all (early init)
            return False
    h, n, d = q.shape
    blk = _flash_block_for(n, d)
    bwd = _flash_bwd_block_for(n, d)
    if _PALLAS_INTERPRET and not (blk == n and bwd == n):
        return False
    return (k.shape[0] == h and d % 128 == 0
            and blk != 0 and n % blk == 0 and bwd != 0 and n % bwd == 0
            and q.dtype in (jnp.float32, jnp.bfloat16)
            and k.dtype == q.dtype and v.dtype == q.dtype)


# Combined-K+V byte ceiling for the GQA expand dispatch (HBM is ~16 GB
# on the measured chip; 2 GiB keeps the expansion a rounding error next
# to the score-block working set while admitting every realistic
# (heads, seq) this framework records).
_GQA_EXPAND_BYTES = 2 << 30


def _flash_dispatch_plan(q, k, v):
    """How (if at all) these operands reach the Pallas kernel:
    ``("direct", blk, blk_bwd, 1)``, ``("expand", blk, blk_bwd,
    groups)``, or ``None`` (the jnp engine). ``blk`` is the forward
    block edge, ``blk_bwd`` the (independently pinnable) edge of the
    eight dq/dkv blocks. GQA/MQA shapes whose broadcast K/V fit
    ``_GQA_EXPAND_BYTES`` are dispatched by expanding — chip-measured
    (32k, 8q/2kv, causal bf16, two runs): expand+kernel 130.7-134.1
    fwd / 100.0-106.4 grad TFLOP/s vs 48.4 / 47.5 for the folded jnp
    path, i.e. the repeat's HBM cost is a ~2.7x win. The gradient through ``jnp.repeat`` sums
    per-group dk/dv exactly as the folded path does."""
    h, n, d = q.shape
    if _pallas_flash_eligible(q, k, v):
        return ("direct", _flash_block_for(n, d), _flash_bwd_block_for(n, d), 1)
    hkv = k.shape[0]
    if hkv and h % hkv == 0 and h > hkv:
        ek = jax.ShapeDtypeStruct((h, n, d), k.dtype)
        ev = jax.ShapeDtypeStruct((h, n, d), v.dtype)
        if (2 * h * n * d * q.dtype.itemsize <= _GQA_EXPAND_BYTES
                and _pallas_flash_eligible(q, ek, ev)):
            return ("expand", _flash_block_for(n, d),
                    _flash_bwd_block_for(n, d), h // hkv)
    return None


def _plan_stamp(plan) -> str:
    """Provenance string for a dispatch plan: ``pallas:b<blk>`` plus
    ``:bw<blk_bwd>`` when the backward edge differs from the forward's
    and ``:kvx<groups>`` for the GQA expand form — the exact
    configuration recorders must gate and stamp."""
    kind, blk, bwd, groups = plan
    stamp = f"pallas:b{blk}"
    if bwd != blk:
        stamp += f":bw{bwd}"
    if kind == "expand":
        stamp += f":kvx{groups}"
    return stamp


# The multi-device ring's per-hop engine: run the Pallas flash kernel on
# each arriving K/V block instead of the jnp `_block_update` fold
# (chip-measured 132-147 vs 47-49 TFLOP/s — see the `_TPU_FLASH` note),
# and merge hops with the exact online-softmax combine. MOMP_RING_HOP=0
# pins the ring to the jnp fold (which remains the CPU/interpret oracle
# and the fallback for hop shapes the kernel doesn't take).
_RING_HOP = os.environ.get("MOMP_RING_HOP", "1") != "0"

# The ring BACKWARD's per-hop engine (the repo-owned hop kernels in
# ops/flash_hop_bwd — see that module for why the bundled kernel's
# backward can't serve here). MOMP_RING_HOP_BWD=0 pins the backward
# hops to the jnp _flash_block_grads fold while the forward hops keep
# the kernel; MOMP_RING_HOP=0 pins both directions.
_RING_HOP_BWD = os.environ.get("MOMP_RING_HOP_BWD", "1") != "0"

# Causal-zigzag forward hop dispatch: decompose each hop's live
# quarter-blocks into kernel calls per half-chunk (hop 0 = causal
# triangles, later hops = unmasked rectangles) merged through
# _merge_partials. MOMP_RING_ZZ=0 pins causal zigzag to the jnp fold
# (the pre-decomposition behaviour).
_RING_ZZ = os.environ.get("MOMP_RING_ZZ", "1") != "0"

# Hop prefetch: issue hop i+1's K/V rotation before hop i's flash
# kernel launches. The hopflash loops always had ONE rotation in
# flight (issued at the top of each hop, consumed at the top of the
# next); the prefetched schedule carries TWO K/V slots — the block
# being folded and the block in flight — so every rotation gets two
# kernel launches of hiding slack instead of one. Same p-1 rotations,
# same folds in the same order (parity is bit-exact); only the issue
# points move earlier. Needs p >= 3 (with fewer devices there is no
# second transfer to deepen the pipeline with) and applies to the
# hopflash forward, its causal-zigzag decomposition, and the
# travelling-dk/dv backward's K/V trip (the dk/dv accumulator
# rotations cannot prefetch — each carries the hop's own
# contribution). MOMP_RING_PREFETCH=0 is the kill switch back to the
# single-slot schedule; the guarded recovery path pins it off with
# the hop kernels (the recovered trace is the plain jnp fold).
_RING_PREFETCH = os.environ.get("MOMP_RING_PREFETCH", "1") != "0"


def _ring_prefetch_on(p: int) -> bool:
    """Whether the hopflash loops run the double-slot prefetched
    schedule for a ``p``-device ring (gate + eligibility: a 2-device
    ring has a single transfer — nothing to pipeline deeper)."""
    return _RING_PREFETCH and p > 2


@contextlib.contextmanager
def _ring_hop_pinned(value: bool):
    """Pin the ring-hop engine gates for one dispatch: the guarded
    recovery path in :func:`ring_attention` re-dispatches a poisoned
    fold on the jnp fold oracle by tracing with the hop kernels pinned
    off — BOTH directions, and the hop prefetch with them, so the
    recovered trace is the full single-slot jnp fold (paired with a
    distinct jit-cache key — the flags are read at trace time, not
    part of the cache key)."""
    global _RING_HOP, _RING_HOP_BWD, _RING_PREFETCH
    prev = (_RING_HOP, _RING_HOP_BWD, _RING_PREFETCH)
    _RING_HOP = value
    _RING_HOP_BWD = value
    _RING_PREFETCH = value
    try:
        yield
    finally:
        _RING_HOP, _RING_HOP_BWD, _RING_PREFETCH = prev


def _ring_hop_plan(q, k, v, causal: bool, layout: str):
    """Dispatch plan for the per-hop Pallas ring FORWARD engine, or
    ``None`` (the jnp fold). Operands are the PER-SHARD
    ``(h, n_local, d)`` blocks, so eligibility — block edges, GQA
    expand budget — is judged at hop granularity. The contiguous ring
    needs only the kernel's static causal flag (hop 0 is the diagonal
    triangle, every other unskipped hop is fully unmasked); causal
    zigzag runs HALF-chunk kernel calls (``_ring_forward_hopflash_zz``:
    hop-0 triangles via the same flag, off-diagonal live pairs fully
    unmasked), so its eligibility is judged on the ``(h, n_local/2,
    d)`` half shape — ``MOMP_RING_ZZ=0`` pins it to the jnp fold."""
    if not _RING_HOP:
        return None
    if causal and layout == "zigzag":
        if not _RING_ZZ:
            return None
        h, nl, d = q.shape
        if nl % 2:
            return None
        half = nl // 2
        return _flash_dispatch_plan(
            jax.ShapeDtypeStruct((h, half, d), q.dtype),
            jax.ShapeDtypeStruct((k.shape[0], half, d), k.dtype),
            jax.ShapeDtypeStruct((v.shape[0], half, d), v.dtype))
    return _flash_dispatch_plan(q, k, v)


def _ring_hop_bwd_plan(q, k, v, causal: bool, layout: str):
    """Dispatch plan ``(kind, blk, groups)`` for the per-hop Pallas ring
    BACKWARD engine (``ops.flash_hop_bwd``), or ``None`` (the jnp
    ``_flash_block_grads`` fold). Gated by the forward's eligibility
    machinery — same per-shard block-edge and GQA-expand-budget
    judgement — with the backward edge capped at the hop kernels' VMEM
    budget (``flash_hop_bwd.MAX_BLOCK``: the cap keeps dividing the
    sequence since edges are 128-multiples of powers of two). Causal
    zigzag stays on the jnp fold: its half-chunk gradient decomposition
    isn't implemented (the travelling accumulators would need per-half
    routing), so it is an ineligible shape by definition here."""
    if not (_RING_HOP and _RING_HOP_BWD):
        return None
    if causal and layout == "zigzag":
        return None
    plan = _flash_dispatch_plan(q, k, v)
    if plan is None:
        return None
    from mpi_and_open_mp_tpu.ops import flash_hop_bwd

    kind, _, bwd, groups = plan
    return (kind, min(bwd, flash_hop_bwd.MAX_BLOCK), groups)


def _merge_partials(o1, L1, o2, L2):
    """Online-softmax combine of two NORMALISED attention partials over
    disjoint key sets: ``L = logaddexp(L1, L2)``, ``o = o1·exp(L1-L) +
    o2·exp(L2-L)``. Exact (it is the algebraic merge of the two
    softmaxes' numerators and denominators) and associative, so hops
    may fold in any order. ``o`` rows ``(h, n, d)``, ``L`` ``(h, n)``,
    all float32."""
    L = jnp.logaddexp(L1, L2)
    w1 = jnp.exp(L1 - L)[..., None]
    w2 = jnp.exp(L2 - L)[..., None]
    return o1 * w1 + o2 * w2, L


def _hop_flash_block(q, kb, vb, causal: bool, blk: int, groups: int):
    """One hop's attention through the bundled Pallas kernel: the
    NORMALISED partial output and its per-row logsumexp ``L = m +
    log(l)`` of the scaled scores — the partial :func:`_merge_partials`
    combines, both float32. Calls the kernel's forward impl directly
    with ``save_residuals=True`` (the public ``fa._flash_attention``
    custom_vjp refuses residuals in its fwd): safe here because the
    ring's own ``custom_vjp`` wraps the whole trip, so the kernel's vjp
    is never entered — the travelling-dk/dv ``_ring_flash_bwd`` keeps
    the backward contract. GQA hops broadcast K/V locally per hop
    (plan-budgeted); the ppermutes still carry the un-expanded
    ``(hkv, ...)`` blocks."""
    from jax.experimental.pallas.ops.tpu import flash_attention as fa

    if groups > 1:
        kb, vb = _repeat_heads(kb, vb, groups)
    d = q.shape[-1]
    with _pallas_interpret_calls(fa):
        o, l, m = fa._flash_attention_impl(
            q[None], kb[None], vb[None], None, None, True, causal,
            1.0 / math.sqrt(d), block_b=1, block_q=blk,
            block_k_major=blk, block_k=blk, debug=False)
    L = m[0] + jnp.log(l[0])
    return o[0].astype(jnp.float32), L.astype(jnp.float32)


def _ring_forward_hopflash(axis: str, causal: bool, p: int, q, k, v, plan):
    """The rotate-and-fold forward with the Pallas kernel as the per-hop
    engine (contiguous layout; :func:`_ring_hop_plan` gated). Same ring
    schedule as the jnp fold — double-buffered ppermutes outside the
    causal ``cond`` — but each hop runs the flash kernel to a
    normalised ``(o, L)`` partial and hops merge via
    :func:`_merge_partials` instead of carrying raw ``(o, m, l)``
    state. Hop 0 is the resident diagonal block — the one hop whose
    causal mask is the standard triangle in local coordinates, i.e. the
    kernel's static ``causal`` flag; every later unskipped hop
    (``src < idx``) is fully unmasked. Returns ``(o, L)`` with ``L`` in
    the folded GQA layout ``_ring_flash_bwd`` consumes.

    With :func:`_ring_prefetch_on` the loop runs the double-slot
    prefetched schedule (see the ``_RING_PREFETCH`` note): hop 1 AND
    hop 2 rotations leave before the diagonal kernel, and each loop
    iteration issues hop ``j+2``'s rotation from the arriving buffer
    before folding hop ``j`` — two folds of hiding slack per transfer,
    identical fold order and rotation count."""
    idx = lax.axis_index(axis) if causal else 0
    hkv = k.shape[0]
    g = q.shape[0] // hkv
    _, blk, _, groups = plan
    perm = ring_perm(p, 1)

    # Chaos hook, mirroring the jnp fold's (see _ring_forward): hop 0 is
    # the resident diagonal block outside the fold, so it takes the
    # poison directly; later hops go through the wrapped fold.
    from mpi_and_open_mp_tpu.robust import chaos as _chaos

    _poison = _chaos.hop_poison_spec()
    k0, v0 = (_chaos.poison_hop(k, v, 0, _poison)
              if _poison is not None else (k, v))

    # Issue the first rotation before the diagonal block's kernel call
    # (the jnp fold's double-buffering, same latency-hiding pairing).
    k1 = lax.ppermute(k, axis, perm)
    v1 = lax.ppermute(v, axis, perm)
    prefetch = _ring_prefetch_on(p)
    if prefetch:
        # Hop 2's rotation leaves before the diagonal kernel too — from
        # here on two K/V transfers are in flight at every kernel launch.
        k2 = lax.ppermute(k1, axis, perm)
        v2 = lax.ppermute(v1, axis, perm)
    state = _hop_flash_block(q, k0, v0, causal, blk, groups)

    def fold(j, state, kb, vb):
        # After j forward rotations this block originated on ring
        # position (idx - j) mod p — never the diagonal for j >= 1, so
        # it is either fully unmasked (src < idx, or any hop when
        # non-causal) or entirely in the future and skipped. The
        # ppermutes stay outside the cond (collectives inside a
        # per-device branch would deadlock the ring).
        def take(s):
            o2, L2 = _hop_flash_block(q, kb, vb, False, blk, groups)
            return _merge_partials(s[0], s[1], o2, L2)

        if not causal:
            return take(state)
        src = (idx - j) % p
        return lax.cond(src < idx, take, lambda s: s, state)

    if _poison is not None:
        fold = _chaos.poisoned_fold(fold, _poison)

    if prefetch:

        def hop(j, carry):
            state, kb, vb, kb_in, vb_in = carry
            kb_next = lax.ppermute(kb_in, axis, perm)
            vb_next = lax.ppermute(vb_in, axis, perm)
            state = fold(j, state, kb, vb)
            return state, kb_in, vb_in, kb_next, vb_next

        # Loop issues hops 3..p-1 (two ahead of consumption); the last
        # two arrived blocks fold outside it — same p-1 rotations total.
        state, kb, vb, kb_in, vb_in = lax.fori_loop(
            1, p - 2, hop, (state, k1, v1, k2, v2))
        state = fold(p - 2, state, kb, vb)
        o, L = fold(p - 1, state, kb_in, vb_in)
    else:

        def hop(j, carry):
            state, kb, vb = carry
            kb_next = lax.ppermute(kb, axis, perm)
            vb_next = lax.ppermute(vb, axis, perm)
            state = fold(j, state, kb, vb)
            return state, kb_next, vb_next

        state, kb, vb = lax.fori_loop(1, p - 1, hop, (state, k1, v1))
        o, L = fold(p - 1, state, kb, vb)
    # The kernel emits per-q-head rows; the ring backward consumes the
    # folded GQA layout (row r <-> position r // g, group r % g).
    return o.astype(q.dtype), _fold_groups(L, hkv, g)


def _ring_forward_hopflash_zz(axis: str, p: int, q, k, v, plan):
    """Causal-zigzag rotate-and-fold with the Pallas kernel as the
    per-hop engine. Shard ``idx`` holds half-chunks ``(idx, 2p-1-idx)``;
    the jnp fold's live-pair table (see ``_ring_forward``) decomposes
    into at most two RECTANGULAR kernel calls per half-chunk per hop,
    merged through the exact :func:`_merge_partials` combine:

      hop 0 (resident): (lo,lo) and (hi,hi) are the two diagonal
        TRIANGLES in local coordinates — the kernel's static causal
        flag; (hi,lo) is a fully unmasked half-square.
      hop j >= 1 (src != idx): every live pair is fully unmasked —
        (lo,lo) iff src < idx, (hi,lo) always, (hi,hi) iff src > idx —
        so the kernel runs maskless and the per-device ``cond``s skip
        dead pairs entirely (collectives stay outside, as always).

    Same balanced cost as the jnp zigzag fold (~half a full block per
    hop on EVERY device), kernel-rate arithmetic. Returns ``(o, L)``
    with the lo‖hi half order and folded GQA ``L`` — exactly the
    residual layout ``_ring_flash_bwd``'s zigzag branch consumes."""
    idx = lax.axis_index(axis)
    hkv = k.shape[0]
    g = q.shape[0] // hkv
    nl = q.shape[1]
    half = nl // 2
    _, blk, _, groups = plan
    perm = ring_perm(p, 1)
    q_lo, q_hi = q[:, :half], q[:, half:]

    # Chaos hook, mirroring _ring_forward_hopflash: the resident hop 0
    # takes the poison directly; later hops go through the wrapped fold.
    from mpi_and_open_mp_tpu.robust import chaos as _chaos

    _poison = _chaos.hop_poison_spec()
    k0, v0 = (_chaos.poison_hop(k, v, 0, _poison)
              if _poison is not None else (k, v))

    k1 = lax.ppermute(k, axis, perm)
    v1 = lax.ppermute(v, axis, perm)
    prefetch = _ring_prefetch_on(p)
    if prefetch:
        # Double-slot prefetch, exactly as the contiguous forward: hop
        # 2's rotation also leaves before the resident half-chunk
        # kernels run.
        k2 = lax.ppermute(k1, axis, perm)
        v2 = lax.ppermute(v1, axis, perm)

    k_lo, k_hi = k0[:, :half], k0[:, half:]
    v_lo, v_hi = v0[:, :half], v0[:, half:]
    s_lo = _hop_flash_block(q_lo, k_lo, v_lo, True, blk, groups)
    s_hi = _hop_flash_block(q_hi, k_lo, v_lo, False, blk, groups)
    s_hi = _merge_partials(
        *s_hi, *_hop_flash_block(q_hi, k_hi, v_hi, True, blk, groups))

    def fold(j, state, kb, vb):
        s_lo, s_hi = state
        src = (idx - j) % p
        k_lo, k_hi = kb[:, :half], kb[:, half:]
        v_lo, v_hi = vb[:, :half], vb[:, half:]
        s_lo = lax.cond(
            src < idx,
            lambda s: _merge_partials(
                *s, *_hop_flash_block(q_lo, k_lo, v_lo, False, blk,
                                      groups)),
            lambda s: s, s_lo)
        s_hi = _merge_partials(
            *s_hi, *_hop_flash_block(q_hi, k_lo, v_lo, False, blk, groups))
        s_hi = lax.cond(
            src > idx,
            lambda s: _merge_partials(
                *s, *_hop_flash_block(q_hi, k_hi, v_hi, False, blk,
                                      groups)),
            lambda s: s, s_hi)
        return s_lo, s_hi

    if _poison is not None:
        fold = _chaos.poisoned_fold(fold, _poison)

    if prefetch:

        def hop(j, carry):
            state, kb, vb, kb_in, vb_in = carry
            kb_next = lax.ppermute(kb_in, axis, perm)
            vb_next = lax.ppermute(vb_in, axis, perm)
            state = fold(j, state, kb, vb)
            return state, kb_in, vb_in, kb_next, vb_next

        state, kb, vb, kb_in, vb_in = lax.fori_loop(
            1, p - 2, hop, ((s_lo, s_hi), k1, v1, k2, v2))
        state = fold(p - 2, state, kb, vb)
        s_lo, s_hi = fold(p - 1, state, kb_in, vb_in)
    else:

        def hop(j, carry):
            state, kb, vb = carry
            kb_next = lax.ppermute(kb, axis, perm)
            vb_next = lax.ppermute(vb, axis, perm)
            state = fold(j, state, kb, vb)
            return state, kb_next, vb_next

        state, kb, vb = lax.fori_loop(
            1, p - 1, hop, ((s_lo, s_hi), k1, v1))
        s_lo, s_hi = fold(p - 1, state, kb, vb)
    o = jnp.concatenate([s_lo[0], s_hi[0]], axis=1).astype(q.dtype)
    L = jnp.concatenate([s_lo[1], s_hi[1]], axis=1)
    return o, _fold_groups(L, hkv, g)


def _ring_backward_hopflash(axis: str, causal: bool, p: int, res, do,
                            plan):
    """The travelling-dk/dv ring backward with the repo-owned Pallas hop
    kernels (``ops.flash_hop_bwd``) as the per-hop gradient engine
    (contiguous layout; :func:`_ring_hop_bwd_plan` gated). Identical
    ring schedule and accumulator contract to the jnp path in
    ``_ring_flash_bwd`` — K/V make the second ring trip, each block
    carrying its (dk, dv) accumulator home over ``p`` rotations — but
    every unskipped hop's (dq, dk, dv) block comes from the two kernel
    launches instead of the ``_flash_block_grads`` fold. Hop 0 is
    peeled out of the ``fori_loop``: it is the one hop whose causal
    mask is the local diagonal triangle (the kernels' static ``causal``
    flag); every later unskipped hop (``src < idx``) runs maskless.

    The per-row statistics are hop-invariant, so ``L`` (unfolded from
    the residual's folded GQA layout to per-q-head rows) and ``D =
    rowsum(do·o)`` are lane-broadcast ONCE outside the loop. GQA K/V
    expand per hop inside the taken branch (plan-budgeted, like the
    forward hop engine); dk/dv come back per-q-head and are group-summed
    into the (hkv, ...) travelling accumulators."""
    from mpi_and_open_mp_tpu.ops import flash_hop_bwd

    q, k, v, o, L = res
    idx = lax.axis_index(axis) if causal else 0
    nl, d = q.shape[1:]
    hkv = k.shape[0]
    g = q.shape[0] // hkv
    f32 = jnp.float32
    perm = ring_perm(p, 1)
    _, blk, groups = plan

    D = jnp.sum(do.astype(f32) * o.astype(f32), axis=-1)  # (h, nl)
    L128 = flash_hop_bwd.lane_broadcast(_unfold_groups(L, hkv, g))
    D128 = flash_hop_bwd.lane_broadcast(D)

    def kernel_contrib(kb, vb, diag: bool):
        kbx, vbx = _repeat_heads(kb, vb, groups)
        dqh, dkh, dvh = flash_hop_bwd.hop_block_grads(
            q, do, L128, D128, kbx, vbx, causal=diag and causal,
            blk=blk, interpret=_PALLAS_INTERPRET)
        if g > 1:
            dkh = dkh.reshape(hkv, g, nl, d).sum(axis=1)
            dvh = dvh.reshape(hkv, g, nl, d).sum(axis=1)
        # The hop loop carries dq in the folded GQA layout (it is
        # unfolded once at the end, like the jnp path's).
        return _fold_groups(dqh, hkv, g), dkh, dvh

    def zero3(_):
        return (jnp.zeros((hkv, nl * g, d), f32),
                jnp.zeros((hkv, nl, d), f32),
                jnp.zeros((hkv, nl, d), f32))

    # Hop 0: resident diagonal block, double-buffered like the forward
    # (first rotation issued before the kernel launches; under prefetch
    # the second K/V rotation leaves before them too — the dk/dv
    # accumulator rotations CANNOT prefetch, each carries the hop's own
    # contribution, so only the K/V trip deepens).
    k1 = lax.ppermute(k, axis, perm)
    v1 = lax.ppermute(v, axis, perm)
    prefetch = _ring_prefetch_on(p)
    if prefetch:
        k2 = lax.ppermute(k1, axis, perm)
        v2 = lax.ppermute(v1, axis, perm)
    dq0, dk0, dv0 = kernel_contrib(k, v, True)
    dkb = lax.ppermute(dk0, axis, perm)
    dvb = lax.ppermute(dv0, axis, perm)

    def contribute(j, kb, vb):
        # j >= 1 only: never the diagonal, so either fully unmasked or
        # entirely in the future and skipped (contiguous causal). The
        # ppermutes stay outside the cond (collectives in a per-device
        # branch would deadlock the ring).
        if not causal:
            return kernel_contrib(kb, vb, False)
        src = (idx - j) % p
        return lax.cond(
            src < idx, lambda _: kernel_contrib(kb, vb, False), zero3,
            None)

    if prefetch:

        def hop(j, carry):
            dq, kb, vb, kb_in, vb_in, dkb, dvb = carry
            kb_next = lax.ppermute(kb_in, axis, perm)
            vb_next = lax.ppermute(vb_in, axis, perm)
            dqj, dkj, dvj = contribute(j, kb, vb)
            dkb = lax.ppermute(dkb + dkj, axis, perm)
            dvb = lax.ppermute(dvb + dvj, axis, perm)
            return dq + dqj, kb_in, vb_in, kb_next, vb_next, dkb, dvb

        # Loop issues K/V hops 3..p-1 two ahead of consumption; the
        # last two arrived blocks contribute outside it. Accumulator
        # rotations: hop-0 peel + p-3 loop + the two tail ones = p,
        # same count as the single-slot schedule.
        dq, kb, vb, kb_in, vb_in, dkb, dvb = lax.fori_loop(
            1, p - 2, hop, (dq0, k1, v1, k2, v2, dkb, dvb))
        dqj, dkj, dvj = contribute(p - 2, kb, vb)
        dq = dq + dqj
        dkb = lax.ppermute(dkb + dkj, axis, perm)
        dvb = lax.ppermute(dvb + dvj, axis, perm)
        dqj, dkj, dvj = contribute(p - 1, kb_in, vb_in)
        dq = dq + dqj
        dk = lax.ppermute(dkb + dkj, axis, perm)
        dv = lax.ppermute(dvb + dvj, axis, perm)
    else:

        def hop(j, carry):
            dq, kb, vb, dkb, dvb = carry
            kb_next = lax.ppermute(kb, axis, perm)
            vb_next = lax.ppermute(vb, axis, perm)
            dqj, dkj, dvj = contribute(j, kb, vb)
            dkb = lax.ppermute(dkb + dkj, axis, perm)
            dvb = lax.ppermute(dvb + dvj, axis, perm)
            return dq + dqj, kb_next, vb_next, dkb, dvb

        dq, kb, vb, dkb, dvb = lax.fori_loop(
            1, p - 1, hop, (dq0, k1, v1, dkb, dvb))
        # Last block, then the p-th accumulator rotation lands every
        # (dk, dv) back on its home shard (hop-0 peel + p-2 loop
        # rotations + this one = p, same count as the jnp path).
        dqj, dkj, dvj = contribute(p - 1, kb, vb)
        dq = dq + dqj
        dk = lax.ppermute(dkb + dkj, axis, perm)
        dv = lax.ppermute(dvb + dvj, axis, perm)
    dq = _unfold_groups(dq, hkv, g).astype(q.dtype)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# Traced hop-by-hop ring dispatch (obs.trace): per-hop telemetry.
#
# Per-hop ring spans are impossible from inside the compiled ring: the
# p-1 hops live in one `fori_loop` inside one `shard_map` program — the
# host sees a single dispatch, so there is nothing to bracket. When a
# trace sink is armed (`MOMP_TRACE`, and no chaos plan / guards in the
# way), `ring_attention` therefore re-plans the CONTIGUOUS forward as
# p-1 host-level hop dispatches: each hop issues (1) one jitted
# shard_map ppermute rotation of the K/V blocks — the `ring.hop.transfer`
# span, anchored so the wire time is attributed — then (2) one jitted
# fold of the arrived block into the running normalised (o, L) partial
# via `_merge_partials` — the `ring.hop.fold` span, tagged with the same
# engine stamp `ring_hop_engine_for` reports (the fold runs the real
# per-hop engine: `_hop_flash_block` whenever `_ring_hop_plan` grants a
# plan, else a `_block_update`-based jnp partial). Exactly 2*(p-1)
# `ring.hop.*` spans per attention step; the hop-0 resident diagonal is
# a separate `ring.fold.resident` span (it moves no bytes). The result
# is parity-exact with the fused ring — `_merge_partials` is the exact
# associative combine — but each hop pays a host round trip, so this
# path exists for telemetry, never inside timing brackets. Causal zigzag
# keeps the fused engine (its half-chunk hops don't decompose into
# whole-block host folds) and gets a whole-call span instead.


def _traced_hop_partial(qs, kb, vb, causal_blk: bool, plan):
    """One hop's NORMALISED (o, L) partial on the planned engine — the
    same quantity `_hop_flash_block` emits, computed per shard."""
    if plan is not None:
        _, blk, _, groups = plan
        return _hop_flash_block(qs, kb, vb, causal_blk, blk, groups)
    hq, nl, _ = qs.shape
    if kb.shape[0] != hq:
        kb, vb = _repeat_heads(kb, vb, hq // kb.shape[0])
    rows = jnp.arange(nl)
    o0 = jnp.zeros(qs.shape, jnp.float32)
    m0 = jnp.full((hq, nl), _NEG, jnp.float32)
    l0 = jnp.zeros((hq, nl), jnp.float32)
    o, m, l = _block_update(qs.astype(jnp.float32), kb, vb,
                            rows, rows, None, causal_blk, o0, m0, l0)
    l = jnp.maximum(l, 1e-37)
    return o / l[..., None], m + jnp.log(l)


def _traced_L_spec(axis: str) -> P:
    return P(None, axis)


@functools.partial(jax.jit, static_argnames=("mesh", "axis"))
def _traced_rotate_jit(kb, vb, *, mesh: Mesh, axis: str):
    """One K/V ring rotation — the traced ring's transfer step."""

    def body(kb, vb):
        p = axis_size(axis)
        perm = ring_perm(p, 1)
        return lax.ppermute(kb, axis, perm), lax.ppermute(vb, axis, perm)

    spec = _seq_spec(axis)
    return mesh_lib.shard_map(
        body, mesh=mesh, in_specs=(spec, spec), out_specs=(spec, spec),
        check_vma=False)(kb, vb)


@functools.partial(jax.jit,
                   static_argnames=("mesh", "axis", "causal", "plan"))
def _traced_fold0_jit(q, kb, vb, *, mesh: Mesh, axis: str, causal: bool,
                      plan):
    """Hop 0: the resident diagonal block's partial (the one hop whose
    causal mask is the standard triangle in local coordinates)."""

    def body(qs, kb, vb):
        return _traced_hop_partial(qs, kb, vb, causal, plan)

    spec = _seq_spec(axis)
    return mesh_lib.shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=(spec, _traced_L_spec(axis)), check_vma=False)(q, kb, vb)


@functools.partial(jax.jit,
                   static_argnames=("mesh", "axis", "causal", "plan"))
def _traced_fold_jit(o, L, q, kb, vb, j, *, mesh: Mesh, axis: str,
                     causal: bool, plan):
    """Fold the block that arrived after ``j >= 1`` rotations into the
    running (o, L). ``j`` rides as data (one compile serves every hop).
    After j rotations the block originated on ring position
    ``(idx - j) % p`` — never the diagonal, so it is either fully
    unmasked or (causal, src > idx) entirely in the future and skipped.
    No collectives in here, so the skip `cond` is safe per device."""

    def body(o, L, qs, kb, vb, j):
        def take(state):
            o2, L2 = _traced_hop_partial(qs, kb, vb, False, plan)
            return _merge_partials(state[0], state[1], o2, L2)

        if not causal:
            return take((o, L))
        p = axis_size(axis)
        idx = lax.axis_index(axis)
        src = (idx - j) % p
        return lax.cond(src < idx, take, lambda s: s, (o, L))

    spec = _seq_spec(axis)
    lsp = _traced_L_spec(axis)
    return mesh_lib.shard_map(
        body, mesh=mesh, in_specs=(spec, lsp, spec, spec, spec, P()),
        out_specs=(spec, lsp), check_vma=False)(o, L, q, kb, vb, j)


def _ring_attention_traced(q, k, v, *, mesh: Mesh, axis: str, causal: bool):
    """Hop-by-hop instrumented contiguous ring forward (module comment
    above). Operands arrive already device_put with the ring sharding."""
    from mpi_and_open_mp_tpu.obs import metrics, trace

    p = mesh.shape[axis]
    h, n, d = q.shape
    nl = n // p
    plan = _ring_hop_plan(
        jax.ShapeDtypeStruct((h, nl, d), q.dtype),
        jax.ShapeDtypeStruct((k.shape[0], nl, d), k.dtype),
        jax.ShapeDtypeStruct((v.shape[0], nl, d), v.dtype),
        causal, "contiguous")
    engine = "jnp" if plan is None else _plan_stamp(plan)
    hop_bytes = (k.nbytes + v.nbytes) // p  # per-device K/V block pair
    with trace.span("ring_attention", devices=p, seq=n, heads=h,
                    causal=causal, engine=engine,
                    traced_dispatch=True) as sp:
        with trace.span("ring.fold.resident", engine=engine) as rsp:
            o, L = _traced_fold0_jit(q, k, v, mesh=mesh, axis=axis,
                                     causal=causal, plan=plan)
            rsp.anchor((o, L))
        kb, vb = k, v
        for j in range(1, p):
            with trace.span("ring.hop.transfer", hop=j,
                            bytes=hop_bytes) as tsp:
                kb, vb = _traced_rotate_jit(kb, vb, mesh=mesh, axis=axis)
                tsp.anchor((kb, vb))
            with trace.span("ring.hop.fold", hop=j, engine=engine) as fsp:
                o, L = _traced_fold_jit(o, L, q, kb, vb, jnp.int32(j),
                                        mesh=mesh, axis=axis,
                                        causal=causal, plan=plan)
                fsp.anchor((o, L))
        metrics.inc("ring.hops.fwd", p - 1, engine=engine)
        metrics.inc("ring.steps.traced")
        sp.anchor(o)
    return o.astype(q.dtype)


def ring_hop_engine_for(q, k, v, *, p: int | None = None,
                        causal: bool = True,
                        layout: str = "contiguous") -> str:
    """Shape-aware provenance for the MULTI-DEVICE ring fold: the engine
    each K/V hop of a ``ring_attention`` over these GLOBAL operands
    will run — a ``pallas:b…`` stamp (per-hop kernel; ``:zz`` marks the
    causal-zigzag half-chunk decomposition, whose block edge is sized
    for the half shape) or ``"jnp"`` (the fold oracle). ``p`` defaults
    to the local device count (what ``ring_attention``'s default mesh
    uses). A 1-device ring never enters the ring body; its local engine
    is reported as ``"local:<flash_engine_for stamp>"``. Recorders
    publishing ring timings must stamp artifacts with this, exactly as
    single-device recorders stamp :func:`flash_engine_for`. 4D
    ``(B, heads, seq, d)`` operands stamp the folded-batch engine with
    a ``:b{B}`` suffix (see :func:`_fold_batch`). A trailing ``:pf``
    marks the double-slot hop-prefetch schedule (``_RING_PREFETCH``
    on, ring size > 2): hop ``i+1``'s K/V rotation is issued before
    hop ``i``'s kernel launches."""
    if len(q.shape) == 4:
        probe_q, probe_k, probe_v = _fold_batch_probes(q, k, v)
        return ring_hop_engine_for(
            probe_q, probe_k, probe_v, p=p, causal=causal, layout=layout
        ) + f":b{q.shape[0]}"
    if p is None:
        p = len(jax.devices())
    h, n, d = q.shape
    if p == 1:
        return "local:" + flash_engine_for(q, k, v)
    nl = n // p
    sq = jax.ShapeDtypeStruct((h, nl, d), q.dtype)
    sk = jax.ShapeDtypeStruct((k.shape[0], nl, d), k.dtype)
    sv = jax.ShapeDtypeStruct((v.shape[0], nl, d), v.dtype)
    plan = _ring_hop_plan(sq, sk, sv, causal, layout)
    if plan is None:
        return "jnp"
    stamp = _plan_stamp(plan)
    if causal and layout == "zigzag":
        stamp += ":zz"
    if _ring_prefetch_on(p):
        stamp += ":pf"
    return stamp


def ring_hop_bwd_engine_for(q, k, v, *, p: int | None = None,
                            causal: bool = True,
                            layout: str = "contiguous") -> str:
    """Shape-aware provenance for the ring BACKWARD's per-hop engine:
    ``pallas:b…`` when each hop's (dq, dk, dv) block runs the
    ``ops.flash_hop_bwd`` kernels (``:kvx…`` for the per-hop GQA
    expand), ``"jnp"`` for the ``_flash_block_grads`` fold (causal
    zigzag, ineligible hop shapes, or ``MOMP_RING_HOP_BWD=0`` /
    ``MOMP_RING_HOP=0``). The stamped block edge is the hop kernels'
    effective one — the single-device backward edge capped at
    ``flash_hop_bwd.MAX_BLOCK``. A 1-device ring reports its local
    engine (whose stamp already carries the kernel backward edge when
    it differs). Recorders publishing ring GRADIENT timings must stamp
    artifacts with this, alongside :func:`ring_hop_engine_for`. 4D
    operands fold and stamp ``:b{B}`` exactly as the forward twin; a
    trailing ``:pf`` marks the prefetched K/V trip exactly as the
    forward's (the dk/dv accumulator rotations never prefetch)."""
    if len(q.shape) == 4:
        probe_q, probe_k, probe_v = _fold_batch_probes(q, k, v)
        return ring_hop_bwd_engine_for(
            probe_q, probe_k, probe_v, p=p, causal=causal, layout=layout
        ) + f":b{q.shape[0]}"
    if p is None:
        p = len(jax.devices())
    h, n, d = q.shape
    if p == 1:
        return "local:" + flash_engine_for(q, k, v)
    nl = n // p
    sq = jax.ShapeDtypeStruct((h, nl, d), q.dtype)
    sk = jax.ShapeDtypeStruct((k.shape[0], nl, d), k.dtype)
    sv = jax.ShapeDtypeStruct((v.shape[0], nl, d), v.dtype)
    plan = _ring_hop_bwd_plan(sq, sk, sv, causal, layout)
    if plan is None:
        return "jnp"
    kind, blk, groups = plan
    stamp = f"pallas:b{blk}"
    if kind == "expand":
        stamp += f":kvx{groups}"
    if _ring_prefetch_on(p):
        stamp += ":pf"
    return stamp


def _pallas_flash(q, k, v, causal: bool) -> jnp.ndarray:
    """Dispatch one (heads, seq, d) attention to the bundled Pallas TPU
    flash kernel (batch dim added/stripped; same 1/sqrt(d) scaling as
    ``attention_reference``). Differentiable via the kernel's own
    flash custom_vjp. Blocks are ALWAYS explicit — the kernel's own
    defaults measured 3x slower than the jnp engine on chip, explicit
    512/1024 blocks 2-4x faster (see the ``_TPU_FLASH`` note) — sized
    by :func:`_flash_block_for` (largest validated edge dividing seq
    that keeps >= ``_MIN_GRID`` grid programs per axis — 8k takes b512,
    16k+ take b1024;
    ``MOMP_FLASH_BLOCK=<n>`` overrides uniformly, a measurement knob so
    a chip session can sweep block sizes without code edits; the
    recorders' parity gates cover whatever value is in effect)."""
    from jax.experimental.pallas.ops.tpu import flash_attention as fa

    # eligibility ensured both edges exist and divide seq
    b = _flash_block_for(q.shape[1], q.shape[2])
    bw = _flash_bwd_block_for(q.shape[1], q.shape[2])
    blocks = fa.BlockSizes(
        block_q=b, block_k_major=b, block_k=b, block_b=1,
        block_q_major_dkv=bw, block_k_major_dkv=bw,
        block_k_dkv=bw, block_q_dkv=bw,
        block_k_major_dq=bw, block_k_dq=bw, block_q_dq=bw)
    with _pallas_interpret_calls(fa):
        out = fa.flash_attention(
            q[None], k[None], v[None], causal=causal,
            sm_scale=1.0 / math.sqrt(q.shape[-1]), block_sizes=blocks)
    return out[0].astype(q.dtype)


def _attention_chunked(q, k, v, causal: bool) -> jnp.ndarray:
    """Full local attention, flash-style double chunking (exact softmax).

    On a TPU backend, shapes the bundled Pallas flash kernel takes are
    dispatched to it (:func:`_flash_dispatch_plan` — directly, or by
    broadcasting budget-fitting GQA K/V, a chip-measured ~2.7x win over
    the folded path); everything below describes the jnp engine that
    carries every other case and is the CPU/interpret oracle.

    Scans q AND k/v in ``_Q_CHUNK`` slices so only a ``(h, _Q_CHUNK,
    _Q_CHUNK)`` score block is ever live; causal k blocks entirely in a q
    chunk's future are skipped via ``cond`` (halving the long-context
    FLOPs, like the ring path's hop skipping). Non-multiple sequence
    lengths are padded — padded k positions are masked out, padded q rows
    are computed and discarded — so there is no divisibility cliff.
    GQA/MQA K/V (fewer heads dividing q's) run UN-expanded on the jnp
    engine: query groups are folded into the row axis
    (:func:`_fold_groups`) so no repeated K/V is ever materialised and
    dk/dv come out group-summed. (On TPU, GQA shapes within the expand
    budget take the Pallas kernel with broadcast K/V instead — the
    kernel's throughput beats the folded path by more than the repeat
    costs; the fold carries the rest.) Used by the Ulysses path and by
    single-device rings.

    Differentiation takes the flash-attention backward (``custom_vjp``
    below), NOT autodiff through the scans: reverse-mode of the chunked
    forward saves O(seq²) block residuals even under remat (measured: a
    causal 16k backward OOMs 16 GB HBM, and 8k runs 15x slower than its
    forward), where the flash backward stores only ``(q, k, v, o,
    logsumexp)`` — O(seq·d) — and recomputes each score block from the
    saved row statistics.

    Caveat (measured, JAX 0.8): differentiating THROUGH a ``lax.scan``
    whose body calls this function (e.g. scanning attention layers and
    grad-ing the whole stack) defeats the memory bound — scan
    linearisation stacks per-block forward intermediates across
    iterations even though the custom backward is still the one invoked.
    Unroll such chains (python loop) or keep ``jax.grad`` inside the scan
    body; ``tests/test_context.py::test_flash_backward_residuals_bounded``
    pins the unrolled behaviour.
    """
    h, n, d = q.shape
    if n <= _Q_CHUNK:
        return attention_reference(
            q, *_repeat_heads(k, v, h // k.shape[0]), causal=causal)
    plan = _flash_dispatch_plan(q, k, v)
    if plan is not None:
        kind, _, _, groups = plan
        if kind == "expand":
            k, v = _repeat_heads(k, v, groups)
        return _pallas_flash(q, k, v, causal)
    return _flash_chunked(causal, q, k, v)


def _chunk(x, nc: int, c: int):
    """(h, nc*c, d...) -> (nc, h, c, d...) scan-leading chunk view."""
    h = x.shape[0]
    return x.reshape(h, nc, c, *x.shape[2:]).swapaxes(0, 1)


def _unchunk(x):
    h, c = x.shape[1], x.shape[2]
    y = x.swapaxes(0, 1)
    return y.reshape(h, x.shape[0] * c, *x.shape[3:])


def _fold_groups(x, hkv: int, g: int):
    """(hkv*g, n, d...) -> (hkv, n*g, d...): GQA query heads folded into
    the row axis, g group-rows per position, so every flash einsum runs
    directly against the UN-expanded (hkv, ...) K/V — no ``jnp.repeat``
    materialisation, and dk/dv come out group-summed for free. Row ``r``
    of the folded array holds position ``r // g``."""
    if g == 1:
        return x
    n = x.shape[1]
    return x.reshape(hkv, g, n, *x.shape[2:]).swapaxes(1, 2).reshape(
        hkv, n * g, *x.shape[2:])


def _unfold_groups(x, hkv: int, g: int):
    if g == 1:
        return x
    ng = x.shape[1]
    return x.reshape(hkv, ng // g, g, *x.shape[2:]).swapaxes(1, 2).reshape(
        hkv * g, ng // g, *x.shape[2:])


def _flash_forward(causal: bool, q, k, v):
    """Chunked forward returning ``(o, L)``: the attention output and the
    per-row logsumexp ``L = m + log l`` of the *scaled* scores — the only
    row statistic the flash backward needs to recompute any block's
    normalised probabilities as ``exp(s - L)``. Padded/fully-masked rows
    get ``L = -_NEG`` (huge) so recomputed probabilities underflow to 0.

    GQA/MQA: ``k``/``v`` may carry ``hkv = h // g`` heads; q is folded to
    ``(hkv, n*g, d)`` (see :func:`_fold_groups`) and the returned ``L``
    stays in that FOLDED layout — the backward consumes it directly.
    """
    h, n, d = q.shape
    hkv = k.shape[0]
    g = h // hkv
    c = _Q_CHUNK
    cg = c * g  # folded q rows per chunk
    nc = -(-n // c)
    pad = nc * c - n
    q32 = jnp.pad(q.astype(jnp.float32), ((0, 0), (0, pad), (0, 0)))
    kp = jnp.pad(k, ((0, 0), (0, pad), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, pad), (0, 0)))
    qs = _chunk(_fold_groups(q32, hkv, g), nc, cg)
    ks, vs = _chunk(kp, nc, c), _chunk(vp, nc, c)
    rep = jnp.arange(cg) // g  # folded row -> within-chunk position

    def body_q(_, xs):
        qc, ci = xs
        qpos = ci * c + rep

        def body_k(carry, ys):
            oc, mc, lc = carry
            kb, vb, kj = ys
            kpos = kj * c + jnp.arange(c)
            n_valid = n if pad else None  # padded k tail needs masking

            def upd(args):
                return _block_update(qc, args[0], args[1], qpos, kpos,
                                     n_valid, causal,
                                     args[2], args[3], args[4])

            if causal:
                # Skip k blocks entirely in this q chunk's future.
                oc, mc, lc = lax.cond(
                    kj <= ci, upd,
                    lambda args: (args[2], args[3], args[4]),
                    (kb, vb, oc, mc, lc),
                )
            else:
                oc, mc, lc = upd((kb, vb, oc, mc, lc))
            return (oc, mc, lc), None

        o0 = jnp.zeros((hkv, cg, d), jnp.float32)
        m0 = jnp.full((hkv, cg), _NEG, jnp.float32)
        l0 = jnp.zeros((hkv, cg), jnp.float32)
        (oc, mc, lc), _ = lax.scan(
            body_k, (o0, m0, l0), (ks, vs, jnp.arange(nc)))
        Lc = jnp.where(lc > 0, mc + jnp.log(jnp.maximum(lc, 1e-37)), -_NEG)
        oc = oc / jnp.where(lc > 0, lc, 1.0)[..., None]
        return None, (oc, Lc)

    _, (os_, Ls) = lax.scan(body_q, None, (qs, jnp.arange(nc)))
    o = _unfold_groups(_unchunk(os_), hkv, g)[:, :n, :].astype(q.dtype)
    return o, _unchunk(Ls)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash_chunked(causal: bool, q, k, v):
    return _flash_forward(causal, q, k, v)[0]


def _flash_chunked_fwd(causal: bool, q, k, v):
    o, L = _flash_forward(causal, q, k, v)
    return o, (q, k, v, o, L)


def _flash_chunked_bwd(causal: bool, res, do):
    """Flash-attention backward: recompute each block's probabilities
    from the saved logsumexp in ONE pass over the allowed (q-chunk,
    k-chunk) blocks — each block's p and dp feed dq, dk and dv together
    (dk/dv accumulate into per-k-chunk stacks by indexed adds carried
    through the scans), causal block skipping mirrored from the
    forward. Per block:

        p  = exp(s - L)            (recomputed, masked)
        D  = rowsum(do * o)
        dv = pᵀ do
        dq = scale · [p∘(do vᵀ - D)] k ;  dk = scale · [...]ᵀ q
    """
    q, k, v, o, L = res
    h, n, d = q.shape
    hkv = k.shape[0]
    g = h // hkv
    c = _Q_CHUNK
    cg = c * g
    nc = -(-n // c)
    pad = nc * c - n
    scale = 1.0 / math.sqrt(d)
    f32 = jnp.float32

    def padded(x, fill=0.0):
        return jnp.pad(x.astype(f32), ((0, 0), (0, pad), (0, 0)),
                       constant_values=fill)

    k32, v32 = padded(k), padded(v)
    q32 = _fold_groups(padded(q), hkv, g)
    do32 = _fold_groups(padded(do), hkv, g)
    o32 = _fold_groups(padded(o), hkv, g)
    Lp = L  # saved FOLDED and padded by the forward (pad rows = -_NEG)
    D = jnp.sum(do32 * o32, axis=-1)  # (hkv, nc*c*g)
    qs, dos = _chunk(q32, nc, cg), _chunk(do32, nc, cg)
    ks, vs = _chunk(k32, nc, c), _chunk(v32, nc, c)
    Ls, Ds = _chunk(Lp, nc, cg), _chunk(D, nc, cg)
    ar = jnp.arange(c)
    rep = jnp.arange(cg) // g  # folded row -> within-chunk position

    # ONE pass over the allowed (i, j) block triangle: each block's
    # recomputed p and dp feed dq, dk AND dv together (5 matmuls/block —
    # the separate dq and dk/dv passes each redid s and dp, 7 total).
    # dk/dv accumulate into per-k-chunk stacks via indexed adds carried
    # through the scans; XLA aliases scan carries in place.
    def body_i(carry, xs):
        dks, dvs = carry
        qc, doc, Lc, Dc, ci = xs

        def body_j(inner, ys):
            dqc, dks, dvs = inner
            kb, vb, kj = ys

            def upd(_):
                mask = _mask_from_pos(ci * c + rep, kj * c + ar, n,
                                      causal)
                return _flash_block_grads(qc, doc, Lc, Dc, kb, vb, mask,
                                          scale)

            # Only the small per-block contributions pass through the
            # causal-skip cond; the O(seq) accumulators stay pure scan
            # carries (in-place aliasing is only guaranteed there — an
            # accumulator routed through a cond branch may be copied
            # per block, turning the O(seq) working set quadratic).
            if causal:
                dqj, dkj, dvj = lax.cond(
                    kj <= ci, upd,
                    lambda _: (jnp.zeros((hkv, cg, d), f32),
                               jnp.zeros((hkv, c, d), f32),
                               jnp.zeros((hkv, c, d), f32)),
                    None)
            else:
                dqj, dkj, dvj = upd(None)
            return (dqc + dqj, dks.at[kj].add(dkj),
                    dvs.at[kj].add(dvj)), None

        (dqc, dks, dvs), _ = lax.scan(
            body_j, (jnp.zeros((hkv, cg, d), f32), dks, dvs),
            (ks, vs, jnp.arange(nc)))
        return (dks, dvs), dqc

    z = jnp.zeros((nc, hkv, c, d), f32)
    (dks, dvs), dqs = lax.scan(
        body_i, (z, z), (qs, dos, Ls, Ds, jnp.arange(nc)))
    dq = _unfold_groups(_unchunk(dqs), hkv, g)[:, :n, :].astype(q.dtype)
    dk = _unchunk(dks)[:, :n, :].astype(k.dtype)
    dv = _unchunk(dvs)[:, :n, :].astype(v.dtype)
    return dq, dk, dv


_flash_chunked.defvjp(_flash_chunked_fwd, _flash_chunked_bwd)


def _seq_spec(axis: str) -> P:
    return P(None, axis, None)


def _check_seq(n: int, p: int, what: str) -> None:
    if n % p:
        raise ValueError(
            f"{what}: sequence length {n} not divisible by mesh size {p}; "
            "pad the sequence to a multiple (the framework's uneven-board "
            "handling pads globally the same way)"
        )


def _check_gqa(q, k, v, what: str) -> int:
    """Validate GQA/MQA head counts; returns the group count hq // hkv."""
    hq, hkv = q.shape[0], k.shape[0]
    if v.shape[0] != hkv:
        raise ValueError(
            f"{what}: v has {v.shape[0]} kv heads but k has {hkv}"
        )
    if hq % hkv:
        raise ValueError(
            f"{what}: {hq} query heads not a multiple of {hkv} kv heads"
        )
    return hq // hkv


def _repeat_heads(k, v, groups: int):
    """Broadcast K/V heads across query-head groups. The jnp compute
    paths avoid this entirely (ring and flash-chunked fold query groups
    into the row axis instead — see :func:`_fold_groups`); it serves
    the dense small-n oracle fallback, Ulysses' pre-wire expansion when
    the kv-head count doesn't split over the mesh (and then minimally —
    see ulysses_attention), and the TPU expand dispatch that broadcasts
    budget-fitting GQA K/V into the Pallas kernel
    (:func:`_flash_dispatch_plan`)."""
    if groups == 1:
        return k, v
    return jnp.repeat(k, groups, axis=0), jnp.repeat(v, groups, axis=0)


@functools.partial(
    jax.jit,
    static_argnames=("local_fn", "mesh", "axis", "causal", "layout",
                     "chaos_key"),
)
def _sharded_attention_jit(q, k, v, *, local_fn, mesh: Mesh, axis: str,
                           causal: bool, chaos_key=None, **local_kwargs):
    """Shared jit + ``shard_map`` scaffold for both attention variants;
    ``local_fn`` is the module-level per-shard body (hashable, so the jit
    cache keys stably on it); extra static kwargs (e.g. the ring
    ``layout``) pass through. ``chaos_key`` is a cache salt only
    (``robust.chaos``): injection and engine pins are trace-time
    decisions, so distinct chaos states must never share a trace — it is
    ``None`` (one cache entry, zero overhead) whenever no plan is
    active."""
    del chaos_key
    # Body runs only on a jit-cache miss — i.e. this IS the retrace
    # counter (obs.metrics): every compile of the sharded attention
    # scaffold lands one tick, cache hits land none.
    from mpi_and_open_mp_tpu.obs import metrics as _metrics

    _metrics.inc("jit.retrace", fn="sharded_attention")
    body = functools.partial(local_fn, axis=axis, causal=causal,
                             **local_kwargs)
    spec = _seq_spec(axis)
    return mesh_lib.shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh | None = None,
    axis: str = AXIS_SP,
    causal: bool = False,
    layout: str = "contiguous",
) -> jnp.ndarray:
    """Sequence-parallel attention over a ring mesh axis.

    ``q, k, v``: ``(heads, seq, head_dim)`` with ``seq`` sharded over
    ``axis``. K/V may carry fewer heads (GQA/MQA) as long as they divide
    the query heads. Peak memory per device is O(chunk * seq/p) scores —
    long contexts scale with the ring size. Returns the same sharding.

    ``layout="zigzag"`` (striped ring attention) balances CAUSAL work:
    under the contiguous split every hop's wall-clock is set by
    whichever device's block is unskipped (there always is one), so a
    causal trip costs ~p full-block times despite computing only half
    the scores. Zigzag pre-shards tokens in ``2p`` half-chunks, shard
    ``i`` holding half-chunks ``(i, 2p-1-i)``; each hop then computes
    only its LIVE (q-half x k-half) quarter-blocks (two off the
    diagonal hop, three on it) — uniformly on every device, forward
    and backward — roughly halving the causal trip's critical path. Operands must arrive in zigzag order
    (:func:`zigzag_shard`; invert outputs/gradients with
    :func:`zigzag_unshard`); needs ``seq % (2 * mesh size) == 0``.

    4D ``(B, heads, seq, head_dim)`` operands run B independent
    requests in ONE ring trip: the batch folds into the (unsharded)
    head axis (:func:`_fold_batch` — GQA grouping preserved per
    request, ``ppermute`` payloads carrying every request's K/V block
    per hop), the fold machinery runs unchanged, and the output
    unfolds to ``(B, heads, seq, head_dim)``. Differentiable like the
    3D form; :func:`ring_hop_engine_for` stamps the shape ``:b{B}``.
    """
    if q.ndim == 4:
        if not (k.ndim == v.ndim == 4 and k.shape[0] == q.shape[0]):
            raise ValueError(
                f"ring_attention: batched q {q.shape} needs k/v with the "
                f"same leading batch, got {k.shape} / {v.shape}")
        out = ring_attention(
            _fold_batch(q), _fold_batch(k), _fold_batch(v),
            mesh=mesh, axis=axis, causal=causal, layout=layout)
        return out.reshape(q.shape)
    if mesh is None:
        mesh = mesh_lib.make_mesh_1d(axis=axis)
    p = mesh.shape[axis]
    _check_seq(q.shape[1], p, "ring_attention")
    _check_gqa(q, k, v, "ring_attention")
    if layout not in ("contiguous", "zigzag"):
        # Eagerly: the p == 1 local path never consults the layout, and
        # a typo must not run silently there.
        raise ValueError(f"unknown ring layout {layout!r}")
    if layout == "zigzag" and q.shape[1] % (2 * p):
        raise ValueError(
            f"ring_attention zigzag layout needs seq % (2*mesh) == 0, "
            f"got seq {q.shape[1]} over {p} devices")
    sharding = NamedSharding(mesh, _seq_spec(axis))
    q, k, v = (jax.device_put(x, sharding) for x in (q, k, v))

    def dispatch(key=None):
        return _sharded_attention_jit(
            q, k, v, local_fn=_ring_attention_local, mesh=mesh, axis=axis,
            causal=causal, layout=layout, chaos_key=key)

    from mpi_and_open_mp_tpu.robust import chaos, guards

    plan = chaos.active_plan()
    if plan is None and not guards.guard_env():
        from mpi_and_open_mp_tpu.obs import trace

        if trace.hop_spans_active() and p > 1 and layout == "contiguous":
            # Telemetry dispatch: hop-by-hop with per-hop spans (see the
            # _ring_attention_traced block comment). Parity-exact, but a
            # host round trip per hop — never on the untraced hot path.
            return _ring_attention_traced(q, k, v, mesh=mesh, axis=axis,
                                          causal=causal)
        if trace.enabled():
            # Shapes the hop-by-hop decomposition doesn't cover (1-device
            # local, causal zigzag) or MOMP_TRACE_HOPS=0: whole-call span.
            with trace.span("ring_attention", devices=p, seq=q.shape[1],
                            layout=layout, causal=causal,
                            engine=ring_hop_engine_for(
                                q, k, v, p=p, causal=causal,
                                layout=layout)) as sp:
                out = dispatch()
                sp.anchor(out)
            return out
        # The production hot path: one env check, no validator (a finite
        # check is a full host fetch — see robust.guards module docs).
        return dispatch()
    if not guards.guards_active():
        # Chaos armed with `noguard`: inject, but let the fault land —
        # the test aid that proves injection reaches the fabric.
        return dispatch(chaos.trace_key("ring"))

    # NaN/divergence guard on the hop engine: validate the dispatched
    # fold, and re-dispatch a poisoned one on the jnp fold oracle —
    # injection suppressed (a transient fault must not re-fire on the
    # dispatch that retries it), hop kernel pinned off, fresh trace.
    def primary():
        return dispatch(chaos.trace_key("ring"))

    def jnp_fold_oracle():
        with chaos.suppressed(), _ring_hop_pinned(False):
            return dispatch(("ring", "recover"))

    from mpi_and_open_mp_tpu.obs import trace

    with trace.span("ring_attention", devices=p, seq=q.shape[1],
                    layout=layout, causal=causal, guarded=True) as sp:
        out, stamp, _notes = guards.with_fallback(
            [("hop", primary), ("jnp", jnp_fold_oracle)],
            validator=guards.all_finite)
        sp.set(engine=stamp)
        if stamp.endswith(":recovered"):
            # The funnel emits the trace event — parented to this span.
            guards.record_recovery(f"ring_attention:{stamp}")
        sp.anchor(out)
    return out


def flash_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, causal: bool = False
) -> jnp.ndarray:
    """Single-device flash-chunked attention — the local engine behind
    ``ring_attention``/``ulysses_attention``, exposed for unsharded use
    (one-chip training steps, benches). Exact softmax in O(chunk·seq)
    memory, the flash ``custom_vjp`` backward (O(seq·d) residuals), and
    GQA/MQA K/V heads run un-expanded on the jnp engine (query groups
    fold into the row axis). On TPU, eligible shapes (block-multiple
    seq, MXU-width head dim) run jax's bundled Pallas flash kernel —
    equal-head directly, budget-fitting GQA via broadcast K/V
    (:func:`_flash_dispatch_plan`); ``MOMP_TPU_FLASH=0`` forces the jnp
    engine. Shapes ``(heads, seq, head_dim)``; ``k``/``v`` may carry
    fewer heads as long as they divide ``q``'s. 4D
    ``(B, heads, seq, head_dim)`` operands fold the request batch into
    the head axis (:func:`_fold_batch` — GQA grouping preserved per
    request) and unfold on the way out; one dispatch serves all B."""
    if q.ndim == 4:
        if not (k.ndim == v.ndim == 4 and k.shape[0] == q.shape[0]):
            raise ValueError(
                f"flash_attention: batched q {q.shape} needs k/v with the "
                f"same leading batch, got {k.shape} / {v.shape}")
        out = flash_attention(
            _fold_batch(q), _fold_batch(k), _fold_batch(v), causal=causal)
        return out.reshape(q.shape)
    _check_gqa(q, k, v, "flash_attention")
    return _attention_chunked(q, k, v, causal)


def _ulysses_local(q, k, v, *, axis: str, causal: bool):
    """Per-shard body: all-to-all seq->head re-shard, local attention, back.

    ``lax.all_to_all`` is the third collective family the framework maps onto
    ICI (after ``ppermute`` halos and ``psum`` reductions); the reference has
    no direct analogue — its closest structure is the gather/scatter pair of
    ``life_collect`` (``5-gather/life_mpi.c:178``) done symmetrically by all
    peers at once.
    """
    # (H, n_local, d) -> (H/p, n_global, d): scatter heads, gather sequence.
    qh = lax.all_to_all(q, axis, split_axis=0, concat_axis=1, tiled=True)
    kh = lax.all_to_all(k, axis, split_axis=0, concat_axis=1, tiled=True)
    vh = lax.all_to_all(v, axis, split_axis=0, concat_axis=1, tiled=True)
    # GQA with hkv % p == 0 stays un-expanded end to end: the contiguous
    # q-head block on each device maps exactly onto its kv-head block on
    # the wire, and the flash-chunked path then folds query groups
    # against the (hkv, ...) K/V directly (the small-n dense fallback
    # expands internally).
    oh = _attention_chunked(qh, kh, vh, causal=causal)
    # (H/p, n_global, d) -> (H, n_local, d).
    return lax.all_to_all(oh, axis, split_axis=1, concat_axis=0, tiled=True)


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh | None = None,
    axis: str = AXIS_SP,
    causal: bool = False,
) -> jnp.ndarray:
    """All-to-all (Ulysses-style) sequence-parallel attention.

    Requires ``heads`` divisible by the mesh size (each device computes full
    attention for ``heads/p`` heads). Two ``all_to_all`` collectives per
    call instead of ring hops; exact softmax, no online accumulation
    needed. GQA/MQA K/V heads whose count splits over the mesh stay
    un-expanded end to end (wire and local compute — the flash path
    folds query groups instead); otherwise they are pre-expanded just
    enough to split.
    """
    if mesh is None:
        mesh = mesh_lib.make_mesh_1d(axis=axis)
    p = mesh.shape[axis]
    _check_seq(q.shape[1], p, "ulysses_attention")
    groups = _check_gqa(q, k, v, "ulysses_attention")
    if q.shape[0] % p:
        raise ValueError(
            f"ulysses_attention: {q.shape[0]} heads not divisible by mesh "
            f"size {p}; use ring_attention (no head constraint) instead"
        )
    hkv = k.shape[0]
    if hkv % p:
        # Too few kv heads to split across the mesh: expand pre-wire, but
        # only to the smallest count divisible by p that still divides hq
        # (the local repeat after the all_to_all covers the rest) — full
        # expansion only as a last resort.
        e = hkv * (p // math.gcd(hkv, p))
        factor = e // hkv if q.shape[0] % e == 0 else groups
        k, v = _repeat_heads(k, v, factor)
    sharding = NamedSharding(mesh, _seq_spec(axis))
    q, k, v = (jax.device_put(x, sharding) for x in (q, k, v))
    return _sharded_attention_jit(q, k, v, local_fn=_ulysses_local,
                                  mesh=mesh, axis=axis, causal=causal)
