"""Persistent halo plans: interior/boundary overlap for sharded stencils.

The sequential halo schedule (``parallel.halo``) is the reference's
blocking ghost-row exchange translated to ``ppermute``: every fused round
waits for the full ``(h + 2d, w)`` padded block before ANY compute
starts — exactly the ``MPI_Send``/``MPI_Recv``-then-step serialisation of
``/root/reference/3-life/life_mpi.c:198-209``. PAPERS.md's "Persistent
and Partitioned MPI for Stencil Communication" (arxiv 2508.13370) shows
the fix: derive the exchange ONCE per (mesh, shard shape, depth) as a
persistent plan, and overlap the ghost transfer with the interior cells
that never needed it.

This module is that plan. A frozen :class:`HaloPlan` splits each fused
round of ``k`` steps (ghost depth ``d = k * radius``) into

* an **interior partition** — rows ``[d, h - d)`` of the shard (columns
  for ``col`` layouts), computable from purely local data: ``k`` fused
  steps applied to the RAW shard, each consuming ``radius`` per side, so
  the trimming lands exactly on the interior; and
* a **boundary partition** — two depth-``d`` edge strips, each computed
  from a ``3d``-deep extension ``concat([ghost, edge_2d])`` after the
  ghost ``ppermute`` completes.

The boundary itself can be **partitioned** (``boundary_steps < fuse_steps``):
each edge strip advances in ``boundary_steps``-deep sub-rounds, and every
sub-round's ghost send is issued straight from that strip's freshly
computed cells — per-edge readiness signalling instead of one
barrier-shaped exchange per fused round, arxiv 2508.13370's
``MPI_Pready`` analogue. The interior keeps the full ``fuse_steps``
depth (deeper interior, shallower edges); total ghost volume is
unchanged but moves in ``fuse_steps / boundary_steps`` smaller per-edge
messages that pipeline behind the interior chain.

The ghost permutes are issued FIRST and consumed LAST: they have no data
dependence on the interior compute, so XLA's latency-hiding scheduler
pairs the collective-permute start with a done AFTER the interior stencil
— the ICI transfer hides behind VPU work, the same double-buffered
schedule as the ring-attention hop (``parallel/context.py`` ``hop()``:
step *k*'s edge slices are in flight while step *k*'s interior computes).
The permutes stay unconditional and OUTSIDE any per-device branch or
kernel body — a collective inside a cond/kernel would deadlock the ring
(DESIGN.md §17).

Bit-exactness: interior and boundary apply the SAME per-cell arithmetic
(``step_fn``) to the same neighbourhood values in the same order as the
sequential whole-shard schedule — only the iteration space is
partitioned, so the reassembled shard equals the sequential result
bit-for-bit (integer rules) / value-for-value (floats; no reassociation
is introduced because each output cell's reduction tree is unchanged).
``tests/test_haloplan.py`` fuzzes this for every registry spec.

Engine stamps (ledger/sentinel provenance — ``seq:`` is the downgrade):

* ``overlap:deferred`` — deferred-concat schedule, every backend.
* ``overlap:rdma``     — ghosts move by Pallas async remote copy
  (``MOMP_HALO_RDMA=1``, real TPU, every layout: row/col exchange their
  edge pair over the 1-D ring, cart runs the two-phase corner exchange —
  y edges first, then x edges carrying the corner words); schedule
  unchanged.
* ``…:pb{b}``          — suffix on either overlap stamp when the
  boundary is partitioned at ``boundary_steps = b < fuse_steps``.
* ``overlap:packed``   — the bit-sliced twin (``ops.bitlife``
  ``make_overlap_steppers``): 32 boards per halo word.
* ``seq:halo`` / ``seq:packed`` — the sequential fallback, stamped with
  the reason in :attr:`HaloPlan.why`.

``MOMP_HALO_OVERLAP=0`` is the kill switch (read at PLAN time, so a
long-lived process re-plans under the flag, not under import order).
Degenerate geometry — a 1-shard axis, or a shard too shallow to hold a
non-empty interior (``extent <= 2d``) — falls back to the sequential
schedule rather than wrapping garbage.
"""

from __future__ import annotations

import dataclasses
import functools
import os

import jax
import jax.numpy as jnp
from jax import lax

from mpi_and_open_mp_tpu.parallel import halo

ENV_OVERLAP = "MOMP_HALO_OVERLAP"
ENV_RDMA = "MOMP_HALO_RDMA"

LAYOUTS = ("row", "col", "cart")


def overlap_enabled() -> bool:
    """The ``MOMP_HALO_OVERLAP`` kill switch (default ON)."""
    return os.environ.get(ENV_OVERLAP, "1") != "0"


def rdma_requested() -> bool:
    """Whether ``MOMP_HALO_RDMA=1`` asks for the explicit Pallas
    async-remote-copy ghost path (default OFF: the deferred ``ppermute``
    schedule already overlaps via XLA's latency-hiding scheduler, and
    the RDMA kernels are the chip rung the r08 queue exercises —
    ``launchers/queue_r08/30_partitioned_halo_ring.sh``; see DESIGN.md
    §20 for the layout matrix)."""
    return os.environ.get(ENV_RDMA, "0") == "1"


@dataclasses.dataclass(frozen=True)
class HaloPlan:
    """One (mesh topology, shard shape, depth, pack layout) exchange
    schedule, derived once and reused every round — the persistent-
    request analogue of arxiv 2508.13370's ``MPI_Psend_init``."""

    layout: str                  # row | col | cart
    mesh_axes: tuple[int, int]   # (py, px) mesh axis sizes
    shard_shape: tuple[int, int] # local (h, w) cell extent per shard
    radius: int
    fuse_steps: int
    boundary_steps: int          # edge sub-round depth; == fuse_steps
                                 # for the coupled (one-exchange) round
    channels: int
    pack_layout: str             # "cell" | "packed"
    depth: int                   # radius * fuse_steps, ghost cells/side
    overlap: bool                # interior/boundary schedule active
    engine: str                  # provenance stamp (module docstring)
    why: str                     # reason overlap was declined ("" if on)


def _overlap_axis(layout: str) -> str:
    """The axis whose exchange the plan overlaps: the sharded row axis
    for ``row``/``cart`` (cart's x exchange on the deferred path stays
    sequential — its ghosts feed the y ghosts' corners, a real data
    dependence; the RDMA rung folds it into phase 2 of the corner
    exchange), the column axis for ``col``."""
    return "x" if layout == "col" else "y"


@functools.lru_cache(maxsize=512)
def _plan(layout: str, mesh_axes: tuple[int, int],
          shard_shape: tuple[int, int], radius: int, fuse_steps: int,
          boundary_steps: int, channels: int, pack_layout: str,
          enabled: bool, rdma: bool) -> HaloPlan:
    depth = radius * fuse_steps
    py, px = mesh_axes
    h, w = shard_shape
    axis = _overlap_axis(layout)
    shards = py if axis == "y" else px
    extent = h if axis == "y" else w

    def seq(why: str) -> HaloPlan:
        stamp = "seq:packed" if pack_layout == "packed" else "seq:halo"
        return HaloPlan(layout, mesh_axes, shard_shape, radius,
                        fuse_steps, fuse_steps, channels, pack_layout,
                        depth, False, stamp, why)

    if layout not in LAYOUTS:
        raise ValueError(f"layout must be one of {LAYOUTS}, got {layout!r}")
    if (boundary_steps < 1 or boundary_steps > fuse_steps
            or fuse_steps % boundary_steps):
        raise ValueError(
            f"boundary_steps={boundary_steps} must divide "
            f"fuse_steps={fuse_steps}")
    if pack_layout == "packed" and boundary_steps != fuse_steps:
        raise ValueError(
            "packed frames keep the coupled boundary depth "
            "(boundary_steps == fuse_steps)")
    if not enabled:
        return seq(f"{ENV_OVERLAP}=0")
    if shards <= 1:
        return seq(f"1-shard {axis} axis: nothing to overlap")
    if extent <= 2 * depth:
        return seq(
            f"shard {axis} extent {extent} <= 2*depth {2 * depth}: "
            "empty interior")
    if pack_layout == "packed":
        engine = "overlap:packed"
    elif rdma and jax.default_backend() == "tpu":
        engine = "overlap:rdma"
    else:
        engine = "overlap:deferred"
    if boundary_steps != fuse_steps:
        engine += f":pb{boundary_steps}"
    return HaloPlan(layout, mesh_axes, shard_shape, radius, fuse_steps,
                    boundary_steps, channels, pack_layout, depth, True,
                    engine, "")


def plan_halo(layout: str, mesh_axes: tuple[int, int],
              shard_shape: tuple[int, int], radius: int,
              fuse_steps: int = 1, *, boundary_steps: int | None = None,
              channels: int = 1,
              pack_layout: str = "cell") -> HaloPlan:
    """Derive (or fetch) the persistent plan for one geometry. The env
    kill switch and the RDMA opt-in are part of the cache key: flipping
    ``MOMP_HALO_OVERLAP`` mid-process yields a fresh plan, never a stale
    cached schedule. ``boundary_steps`` (default: coupled, ==
    ``fuse_steps``) partitions the boundary into shallower per-edge
    sub-rounds; it must divide ``fuse_steps``."""
    bs = fuse_steps if boundary_steps is None else int(boundary_steps)
    return _plan(layout, tuple(mesh_axes), tuple(shard_shape),
                 int(radius), int(fuse_steps), bs, int(channels),
                 pack_layout, overlap_enabled(), rdma_requested())


def _note_schedule(plan: HaloPlan) -> None:
    """Trace-time metrics hook, same discipline as
    ``halo._note_exchange``: counts schedules TRACED per engine stamp —
    zero overlap traces means the overlap path never engaged."""
    from mpi_and_open_mp_tpu.obs import metrics

    metrics.inc("halo.schedule.traced", engine=plan.engine,
                layout=plan.layout)


# --------------------------------------------------------------- ghost moves


def ghosts_y(block: jnp.ndarray, depth: int,
             axis_name: str = "y") -> tuple[jnp.ndarray, jnp.ndarray]:
    """The y ghost pair ``(top, bot)`` by ring ``ppermute`` — the same
    slices :func:`halo.halo_pad_y` concatenates, WITHOUT the concat, so
    the interior compute can proceed while they fly. Chaos hook on the
    top ghost, mirroring the sequential path's injection point."""
    halo._note_exchange("y-overlap", axis_name)
    p = halo._axis_size(axis_name)
    top = halo._chaos_ghost(lax.ppermute(
        block[..., -depth:, :], axis_name, halo.ring_perm(p, 1)))
    bot = lax.ppermute(
        block[..., :depth, :], axis_name, halo.ring_perm(p, -1))
    return top, bot


def ghosts_x(block: jnp.ndarray, depth: int,
             axis_name: str = "x") -> tuple[jnp.ndarray, jnp.ndarray]:
    """The x ghost pair ``(left, right)`` — :func:`ghosts_y` transposed
    to the last axis (cf. ``halo.halo_pad_x``)."""
    halo._note_exchange("x-overlap", axis_name)
    p = halo._axis_size(axis_name)
    left = halo._chaos_ghost(lax.ppermute(
        block[..., -depth:], axis_name, halo.ring_perm(p, 1)))
    right = lax.ppermute(
        block[..., :depth], axis_name, halo.ring_perm(p, -1))
    return left, right


def packed_ghosts_y(q: jnp.ndarray, h: int,
                    axis_name: str = "y") -> tuple[jnp.ndarray, jnp.ndarray]:
    """Packed-frame y ghost pair ``(top, bot)``, ``h`` words per side —
    the deferred form of ``halo.packed_halo_y``'s ``pad == 0`` path (the
    packed overlap plan is gated to exact frames; padded frames stay on
    the sequential funnel-shift path). One halo word carries 32 boards'
    worth of ghost rows — the overlap win multiplied."""
    halo._note_exchange("packed_y-overlap", axis_name)
    p = halo._axis_size(axis_name)
    top = halo._chaos_ghost(
        lax.ppermute(q[-h:], axis_name, halo.ring_perm(p, 1)))
    bot = lax.ppermute(q[:h], axis_name, halo.ring_perm(p, -1))
    return top, bot


# ------------------------------------------- Pallas async remote copy (TPU)


def _rdma_edge_pair(fwd_edge: jnp.ndarray, bwd_edge: jnp.ndarray,
                    axis_name: str, p: int, *, collective_id: int
                    ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """One ghost-pair exchange by explicit Pallas async remote copy.

    Each device starts two RDMAs — ``fwd_edge`` into the ring
    successor's first output buffer, ``bwd_edge`` into the
    predecessor's second — after a neighbour barrier (both peers must
    have entered the kernel before a remote write may land). Returns
    ``(from_prev, from_next)``: the predecessor's ``fwd_edge`` and the
    successor's ``bwd_edge``. Semantically identical to a ``ppermute``
    pair; the difference is WHO schedules the transfer: here the DMA
    engines are driven directly instead of through the
    collective-permute lowering. Real-TPU only (``MOMP_HALO_RDMA=1``) —
    the r08 launcher exercises it on chip; CPU CI stays on the deferred
    ``ppermute`` schedule. Transport only: chaos injection and ghost
    orientation live in the ``_rdma_ghosts_*`` wrappers so every layout
    funnels through ``halo._chaos_ghost`` exactly like the deferred
    path.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(fwd, bwd, prev_out, next_out, s1, r1, s2, r2):
        i = lax.axis_index(axis_name)
        nxt = lax.rem(i + 1, p)
        prv = lax.rem(i + p - 1, p)
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(
            barrier, 1, device_id=(nxt,),
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_signal(
            barrier, 1, device_id=(prv,),
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_wait(barrier, 2)
        send_fwd = pltpu.make_async_remote_copy(
            src_ref=fwd, dst_ref=prev_out, send_sem=s1, recv_sem=r1,
            device_id=(nxt,), device_id_type=pltpu.DeviceIdType.LOGICAL)
        send_bwd = pltpu.make_async_remote_copy(
            src_ref=bwd, dst_ref=next_out, send_sem=s2, recv_sem=r2,
            device_id=(prv,), device_id_type=pltpu.DeviceIdType.LOGICAL)
        send_fwd.start()
        send_bwd.start()
        send_fwd.wait()
        send_bwd.wait()

    from_prev, from_next = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct(fwd_edge.shape, fwd_edge.dtype),
                   jax.ShapeDtypeStruct(bwd_edge.shape, bwd_edge.dtype)),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)] * 2,
        out_specs=[pl.BlockSpec(memory_space=pltpu.ANY)] * 2,
        scratch_shapes=[pltpu.SemaphoreType.DMA] * 4,
        compiler_params=pltpu.TPUCompilerParams(
            collective_id=collective_id),
    )(fwd_edge, bwd_edge)
    return from_prev, from_next


def _rdma_ghosts_y(block: jnp.ndarray, depth: int, axis_name: str,
                   p: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """:func:`ghosts_y` by RDMA — bottom edge forward, top edge
    backward over the y ring (row/cart layouts); chaos hook on the top
    ghost, mirroring the deferred path's injection point."""
    top, bot = _rdma_edge_pair(
        block[..., -depth:, :], block[..., :depth, :], axis_name, p,
        collective_id=13)
    return halo._chaos_ghost(top), bot


def _rdma_ghosts_x(block: jnp.ndarray, depth: int, axis_name: str,
                   p: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """:func:`ghosts_x` by RDMA — the x-mirror schedule for the ``col``
    layout: right edge forward, left edge backward over the x ring."""
    left, right = _rdma_edge_pair(
        block[..., -depth:], block[..., :depth], axis_name, p,
        collective_id=14)
    return halo._chaos_ghost(left), right


def _rdma_ghosts_cart(block: jnp.ndarray, depth: int,
                      mesh_axes: tuple[int, int]
                      ) -> tuple[jnp.ndarray, jnp.ndarray,
                                 jnp.ndarray, jnp.ndarray]:
    """Two-phase cart corner exchange by RDMA: y edges first, then x
    edges carrying the corner words.

    Phase 1 moves the raw y edge pair over the y ring. Phase 2 moves
    the x edge pair OF THE Y-PADDED BLOCK over the x ring — each
    ``(h + 2d, d)`` column strip's first/last ``d`` rows are phase 1's
    freshly landed ghosts, so the diagonal corner words ride the x
    exchange without a third (diagonal) transfer, the same forwarding
    the sequential schedule gets from ``halo.halo_pad_2d``'s pad-x-
    then-pad-y order. Returns ``(top, bot, left, right)`` with
    ``top``/``bot`` of shape ``(..., d, w)`` and ``left``/``right`` of
    shape ``(..., h + 2d, d)`` (corners included)."""
    d = depth
    py, px = mesh_axes
    top, bot = _rdma_edge_pair(
        block[..., -d:, :], block[..., :d, :], "y", py,
        collective_id=13)
    top = halo._chaos_ghost(top)
    pady = jnp.concatenate([top, block, bot], axis=-2)
    left, right = _rdma_edge_pair(
        pady[..., -d:], pady[..., :d], "x", px, collective_id=14)
    left = halo._chaos_ghost(left)
    return top, bot, left, right


# --------------------------------------------------------- fused schedules


def _steps(step_fn, padded: jnp.ndarray, k: int) -> jnp.ndarray:
    for _ in range(k):
        padded = step_fn(padded)
    return padded


def overlap_fused_step(plan: HaloPlan, step_fn, block: jnp.ndarray
                       ) -> jnp.ndarray:
    """One overlapped fused round of ``k = plan.fuse_steps`` steps.

    ``step_fn`` consumes one ``radius`` of halo per side per call (the
    ``stencils.step_padded`` contract). Ghost permutes are issued before
    the interior compute and consumed after it; the three partitions
    reassemble by concat into exactly the sequential round's result.
    Must run inside ``shard_map`` with the layout's axes in scope.
    """
    if not plan.overlap:
        return sequential_fused_step(plan, step_fn, block)
    if plan.boundary_steps != plan.fuse_steps:
        return _partitioned_fused_step(plan, step_fn, block)
    _note_schedule(plan)
    k, d = plan.fuse_steps, plan.depth
    rdma = plan.engine.startswith("overlap:rdma")
    if plan.layout == "col":
        # x-mirror of the row schedule: interior pads y locally (the
        # unsharded axis wraps itself), boundary strips extend in x.
        if rdma:
            left, right = _rdma_ghosts_x(block, d, "x",
                                         plan.mesh_axes[1])
        else:
            left, right = ghosts_x(block, d)
        wrapped = jnp.concatenate(
            [block[..., -d:, :], block, block[..., :d, :]], axis=-2)
        interior = _steps(step_fn, wrapped, k)
        lead = jnp.concatenate([left, block[..., : 2 * d]], axis=-1)
        tail = jnp.concatenate([block[..., -2 * d:], right], axis=-1)
        lead = _steps(
            step_fn, jnp.concatenate(
                [lead[..., -d:, :], lead, lead[..., :d, :]], axis=-2), k)
        tail = _steps(
            step_fn, jnp.concatenate(
                [tail[..., -d:, :], tail, tail[..., :d, :]], axis=-2), k)
        return jnp.concatenate([lead, interior, tail], axis=-1)

    if plan.layout == "cart" and rdma and plan.mesh_axes[1] > 1:
        # Two-phase corner exchange inside the RDMA kernels: y edges
        # first, then x edges carrying the corner words — both axes'
        # ghosts fly while the interior computes (the deferred cart
        # path below still serialises the x exchange up front).
        top2, bot2, left, right = _rdma_ghosts_cart(
            block, d, plan.mesh_axes)
        base = jnp.concatenate(
            [left[..., d:-d, :], block, right[..., d:-d, :]], axis=-1)
        top = jnp.concatenate(
            [left[..., :d, :], top2, right[..., :d, :]], axis=-1)
        bot = jnp.concatenate(
            [left[..., -d:, :], bot2, right[..., -d:, :]], axis=-1)
        interior = _steps(step_fn, base, k)
        lead = _steps(
            step_fn, jnp.concatenate([top, base[..., : 2 * d, :]],
                                     axis=-2), k)
        tail = _steps(
            step_fn, jnp.concatenate([base[..., -2 * d:, :], bot],
                                     axis=-2), k)
        return jnp.concatenate([lead, interior, tail], axis=-2)

    # row / cart: overlap the y exchange. Deferred cart first completes
    # the x exchange sequentially (its ghost columns feed the y ghosts'
    # corners — the reference's two-phase order, life_cart.c:275-279);
    # row wraps x locally. Either way `base` carries d ghost columns.
    if plan.layout == "cart":
        base = halo.halo_pad_x(block, "x", d)
    else:
        base = jnp.concatenate(
            [block[..., -d:], block, block[..., :d]], axis=-1)
    if rdma:
        top, bot = _rdma_ghosts_y(base, d, "y", plan.mesh_axes[0])
    else:
        top, bot = ghosts_y(base, d)
    interior = _steps(step_fn, base, k)
    lead = _steps(
        step_fn, jnp.concatenate([top, base[..., : 2 * d, :]], axis=-2), k)
    tail = _steps(
        step_fn, jnp.concatenate([base[..., -2 * d:, :], bot], axis=-2), k)
    return jnp.concatenate([lead, interior, tail], axis=-2)


def _partitioned_fused_step(plan: HaloPlan, step_fn, block: jnp.ndarray
                            ) -> jnp.ndarray:
    """The partitioned-boundary round: interior keeps the full
    ``k = fuse_steps`` fuse; each edge strip advances in
    ``b = boundary_steps`` sub-rounds, exchanging ``radius * b``-deep
    per-edge ghosts whose sends are issued straight from the strip's
    just-computed cells (per-edge readiness, no whole-round barrier —
    the ``MPI_Pready`` shape of arxiv 2508.13370). Sub-round ``j``'s
    ghost is the neighbour strip's state at step ``j * b``, so the
    reassembled shard is bit-identical to the coupled round: every
    output cell sees the same neighbourhood values through the same
    reduction tree, only sliced along different message boundaries.
    Band extents shrink by ``radius * b`` per side per sub-round along
    the unsharded axis exactly as the coupled strips shrink over ``k``
    fused applications."""
    _note_schedule(plan)
    k, d, b = plan.fuse_steps, plan.depth, plan.boundary_steps
    e = plan.radius * b
    rdma = plan.engine.startswith("overlap:rdma")
    if plan.layout == "col":
        base = jnp.concatenate(
            [block[..., -d:, :], block, block[..., :d, :]], axis=-2)
        interior = _steps(step_fn, base, k)
        lead, tail = base[..., : 2 * d], base[..., -2 * d:]
        p = halo._axis_size("x")
        for _ in range(k // b):
            halo._note_exchange("x-part", "x")
            if rdma:
                left, right = _rdma_edge_pair(
                    tail[..., -e:], lead[..., :e], "x", p,
                    collective_id=14)
                left = halo._chaos_ghost(left)
            else:
                left = halo._chaos_ghost(lax.ppermute(
                    tail[..., -e:], "x", halo.ring_perm(p, 1)))
                right = lax.ppermute(
                    lead[..., :e], "x", halo.ring_perm(p, -1))
            lead = _steps(
                step_fn, jnp.concatenate([left, lead], axis=-1), b)
            tail = _steps(
                step_fn, jnp.concatenate([tail, right], axis=-1), b)
        return jnp.concatenate([lead, interior, tail], axis=-1)

    # row / cart: bands along y. Cart pre-pads x sequentially (corners
    # ride the x ghosts, which then shrink with the band), row wraps x
    # locally; either way each band starts with d ghost columns and
    # narrows by e per side per sub-round.
    if plan.layout == "cart":
        base = halo.halo_pad_x(block, "x", d)
    else:
        base = jnp.concatenate(
            [block[..., -d:], block, block[..., :d]], axis=-1)
    interior = _steps(step_fn, base, k)
    lead, tail = base[..., : 2 * d, :], base[..., -2 * d:, :]
    p = halo._axis_size("y")
    for _ in range(k // b):
        halo._note_exchange("y-part", "y")
        if rdma:
            top, bot = _rdma_edge_pair(
                tail[..., -e:, :], lead[..., :e, :], "y", p,
                collective_id=13)
            top = halo._chaos_ghost(top)
        else:
            top = halo._chaos_ghost(lax.ppermute(
                tail[..., -e:, :], "y", halo.ring_perm(p, 1)))
            bot = lax.ppermute(
                lead[..., :e, :], "y", halo.ring_perm(p, -1))
        lead = _steps(step_fn, jnp.concatenate([top, lead], axis=-2), b)
        tail = _steps(step_fn, jnp.concatenate([tail, bot], axis=-2), b)
    return jnp.concatenate([lead, interior, tail], axis=-2)


def sequential_fused_step(plan: HaloPlan, step_fn, block: jnp.ndarray
                          ) -> jnp.ndarray:
    """The sequential (blocking-concat) round — the historical
    ``halo_pad_*`` schedule, kept callable from the same plan so the A/B
    and the kill switch measure schedules, not code paths."""
    _note_schedule(plan)
    d = plan.depth
    if plan.layout == "row":
        padded = halo.halo_pad_y(jnp.concatenate(
            [block[..., -d:], block, block[..., :d]], axis=-1), "y", d)
    elif plan.layout == "col":
        padded = halo.halo_pad_x(jnp.concatenate(
            [block[..., -d:, :], block, block[..., :d, :]], axis=-2),
            "x", d)
    else:
        padded = halo.halo_pad_2d(block, "y", "x", d)
    return _steps(step_fn, padded, plan.fuse_steps)


def fused_step(plan: HaloPlan, step_fn, block: jnp.ndarray) -> jnp.ndarray:
    """Dispatch one fused round by the plan's schedule."""
    if plan.overlap:
        return overlap_fused_step(plan, step_fn, block)
    return sequential_fused_step(plan, step_fn, block)


# ------------------------------------------------- padded frames for engines
#
# The sparse-sharded engine (stencils.sparse_sharded) gathers tiles out
# of a padded shard frame instead of stepping the whole shard, so it
# needs the PADDING itself, not the fused round. Exposing the exact
# sequential-schedule frame keeps its per-cell arithmetic bit-identical
# to the dense sharded path; the zero-sentinel twin is the exchange-skip
# round — legal only when every shard's boundary band is dead (the
# ghosts it replaces are then provably all-zero; DESIGN.md §18).


def padded_round_block(layout: str, block: jnp.ndarray,
                       depth: int) -> jnp.ndarray:
    """One round's halo-padded shard frame, exchanged exactly as the
    sequential schedule pads it (same concat order, same ppermutes —
    ``halo._note_exchange`` ticks identically). Must run inside
    ``shard_map`` with the layout's axes in scope."""
    d = depth
    if layout == "row":
        return halo.halo_pad_y(jnp.concatenate(
            [block[..., -d:], block, block[..., :d]], axis=-1), "y", d)
    if layout == "col":
        return halo.halo_pad_x(jnp.concatenate(
            [block[..., -d:, :], block, block[..., :d, :]], axis=-2),
            "x", d)
    return halo.halo_pad_2d(block, "y", "x", d)


def padded_round_block_local(layout: str, block: jnp.ndarray,
                             depth: int) -> jnp.ndarray:
    """The zero-sentinel twin of :func:`padded_round_block`: unsharded
    axes wrap locally (they hold the full torus extent, so the local
    wrap IS the true wrap), sharded axes pad with static zeros and no
    collective is issued. Bit-exact iff every shard's boundary band is
    dead — the caller's host-global skip decision, never a per-device
    branch (the ring stays deadlock-free because each compiled program
    is collective-complete)."""
    d = depth
    pad = [(0, 0)] * (block.ndim - 2)
    if layout == "row":
        wrapped = jnp.concatenate(
            [block[..., -d:], block, block[..., :d]], axis=-1)
        return jnp.pad(wrapped, pad + [(d, d), (0, 0)])
    if layout == "col":
        wrapped = jnp.concatenate(
            [block[..., -d:, :], block, block[..., :d, :]], axis=-2)
        return jnp.pad(wrapped, pad + [(0, 0), (d, d)])
    return jnp.pad(block, pad + [(d, d), (d, d)])
