"""Sparse active tiles on a sharded board: live-area cost at mesh scale.

``stencils.sparse`` (PR 13) bounds a single device's per-step cost by
the live area; ``parallel.haloplan`` (PR 15) hides the ghost exchange
behind interior compute. Neither composes with the other: a sharded
board pays full dense cost per shard no matter how dead it is. This
module is the composition — a host-maintained GLOBAL active-tile mask
over a ``shard_map``-sharded board, where each round gathers only the
active tiles of each shard (with radius halos taken from the exchanged
ghost frame), steps them in one collective dispatch, and scatters them
back in place.

**Activation crosses shards for free.** The mask lives in global tile
coordinates: each stepped tile reports a 3x3 border-band change flag
(did cells within ``radius`` of each edge/corner change?), and the host
wakes ``(gy+dy) % ty, (gx+dx) % tx`` — modular arithmetic that neither
knows nor cares where the shard boundaries fall. A glider leaving shard
A wakes the tile it is entering in shard B because the stepped edge
tile read B's cells through the ghost exchange and its band flag fired;
the woken tile is gathered (on B) next round. Bit-exactness is
inherited, not argued: gathered tiles step through the SAME
``engine.step_padded`` arithmetic over the SAME exchanged padding as
the dense sequential schedule, so the reassembled board equals the
dense-sharded board bit-for-bit at every step (integer rules).

**The exchange skip.** A round's ghost payload is exactly the boundary
band (the ``radius``-deep strips along the sharded axes). Every
dispatch also returns one scalar per shard: "is my boundary band
live?". When EVERY shard's band is dead, the next round runs a twin
program whose sharded axes are padded with a static zero sentinel
instead of ``ppermute``d ghosts — bit-exact because the ghosts it
replaces are provably all-zero. The skip decision is made on the HOST
from the global flag, selecting between two compiled programs, so the
collective stays unconditional inside each program and the ring can
never deadlock (DESIGN.md §17 still holds; the legality argument is
§18). ``counters()["exchange_skips"]`` counts the rounds that shipped
no ghosts.

**The crossover ladder survives.** Above ``crossover`` active fraction
the round falls back to the dense sharded runner (PR 15 plans intact)
and the mask rebuilds from the full-board diff — the
``dense:crossover`` rung from PR 13, so adversarial all-alive boards
never regress past one diff. ``MOMP_SPARSE_SHARDED=0`` is the kill
switch (read at PLAN time, same semantics as ``MOMP_HALO_OVERLAP``):
a disabled plan pins every step to the dense sharded path and stamps
``dense:sharded``, which the regression sentinel ranks below any
``sparse*`` stamp — flipping the switch under a recorded sparse
baseline is a provenance downgrade, by construction.
"""

from __future__ import annotations

import dataclasses
import functools
import os

import numpy as np

from . import engine
from .sparse import _dilate
from .spec import StencilSpec

ENV_SPARSE_SHARDED = "MOMP_SPARSE_SHARDED"


def sparse_sharded_enabled() -> bool:
    """The ``MOMP_SPARSE_SHARDED`` kill switch (default ON)."""
    return os.environ.get(ENV_SPARSE_SHARDED, "1") != "0"


@dataclasses.dataclass(frozen=True)
class SparseShardedPlan:
    """One (layout, mesh, shard, tile) sparse-sharded decision, derived
    once per geometry — the sparse twin of ``haloplan.HaloPlan``."""

    layout: str                   # row | col | cart
    mesh_axes: tuple[int, int]    # (py, px)
    shard_shape: tuple[int, int]  # local (h, w) per shard
    tile: int
    crossover: float
    enabled: bool                 # sparse rounds may run at all
    engine: str                   # provenance stamp while sparse wins
    why: str                      # reason sparse was declined ("" if on)


@functools.lru_cache(maxsize=512)
def _plan(layout: str, mesh_axes: tuple[int, int],
          shard_shape: tuple[int, int], radius: int, tile: int,
          crossover: float, enabled: bool) -> SparseShardedPlan:
    h, w = shard_shape

    def off(why: str) -> SparseShardedPlan:
        return SparseShardedPlan(layout, mesh_axes, shard_shape, tile,
                                 crossover, False, "dense:sharded", why)

    if layout not in ("row", "col", "cart"):
        raise ValueError(f"layout must be row|col|cart, got {layout!r}")
    if not enabled:
        return off(f"{ENV_SPARSE_SHARDED}=0")
    if h % tile or w % tile:
        return off(f"tile {tile} does not divide shard {h}x{w}")
    if radius > tile:
        return off(f"radius {radius} exceeds tile {tile}")
    return SparseShardedPlan(
        layout, mesh_axes, shard_shape, tile, crossover, True,
        f"sparse-sharded:{layout}:t{tile}", "")


def plan_sparse_sharded(layout: str, mesh_axes: tuple[int, int],
                        shard_shape: tuple[int, int], radius: int,
                        tile: int, *, crossover: float = 0.5
                        ) -> SparseShardedPlan:
    """Derive (or fetch) the plan for one geometry. The env kill switch
    is part of the cache key — flipping ``MOMP_SPARSE_SHARDED``
    mid-process yields a fresh plan, never a stale cached decision."""
    return _plan(layout, tuple(int(a) for a in mesh_axes),
                 tuple(int(a) for a in shard_shape), int(radius),
                 int(tile), float(crossover), sparse_sharded_enabled())


@functools.lru_cache(maxsize=512)
def _compiled_round(spec: StencilSpec, mesh, layout: str, tile: int,
                    kcap: int, fuse: int, band: int, exchange: bool):
    """Build + jit the collective sparse round for one
    ``(spec, mesh, layout, tile, kcap, fuse, band, exchange)`` tuple.
    Module-level so every :class:`SparseShardedEngine` over the same
    geometry reuses the compile — without this, the bench's min-of-2
    fresh-engine brackets would recompile the whole rung ladder per
    run, and the 2K leg would compile rungs the K leg never reached,
    breaking the chain-differencing cancellation.
    ``StencilSpec`` is a frozen dataclass and ``jax.sharding.Mesh``
    hashes by value, so the key is sound; jit's own trace cache keys
    the shard shape.

    ``fuse`` is the number of steps advanced per dispatch: tiles are
    gathered with a ``radius * fuse``-deep halo (the same data-complete
    margin as a dense fused-halo schedule) and stepped ``fuse`` times
    on device, so the host's per-round sync amortizes over ``fuse``
    steps. Wake flags compare the FINAL state against the PENULTIMATE
    one — an oscillator whose period divides ``fuse`` would look
    settled under an initial-vs-final diff — and the flag bands are
    ``band`` cells deep (``radius *`` the engine's MAX fuse, not this
    round's, so a short tail round still wakes every tile the next
    full-width round could spread into)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from mpi_and_open_mp_tpu.parallel import haloplan
    from mpi_and_open_mp_tpu.parallel import mesh as mesh_lib

    t, r = tile, spec.radius
    d_halo = r * fuse               # gathered halo / ghost depth
    b = min(band, t)                # wake-flag band depth
    lead = {"row": ("y",), "col": ("x",),
            "cart": (("y", "x"),)}[layout]
    pspec = engine.sharded_pspec(layout, 1)
    coords_spec = P(*lead, None, None)
    nvalid_spec = P(*lead)
    flags_spec = P(*lead, None, None, None)

    def body(block, coords, nvalid):
        coords = coords[0]          # (kcap, 2) local tile coords
        valid = jnp.arange(kcap) < nvalid[0]
        if exchange:
            padded = haloplan.padded_round_block(layout, block, d_halo)
        else:
            padded = haloplan.padded_round_block_local(
                layout, block, d_halo)

        def gather(c):
            return lax.dynamic_slice(
                padded, (c[0] * t, c[1] * t),
                (t + 2 * d_halo, t + 2 * d_halo))

        def advance(p):
            # fuse steps at CONSTANT patch shape — step shrinks the
            # frame by 2r, re-zero-padding restores it, and the valid
            # interior shrinks r per step exactly as a shrinking
            # schedule would. fori_loop (not unrolling) keeps the op
            # count and the XLA compile flat in `fuse`; the carry pair
            # keeps the penultimate frame for the consecutive-state
            # wake diff.
            def one(_, carry):
                _prev, cur = carry
                return cur, jnp.pad(engine.step_padded(spec, cur, jnp),
                                    [(r, r), (r, r)])
            return lax.fori_loop(0, fuse, one, (p, p))

        penult, out = jax.vmap(advance)(jax.vmap(gather)(coords))
        # Center t^2 of the final frame is valid after fuse shrinks of
        # r; the penultimate frame is valid one ring wider, so its
        # center crop is too.
        final = out[:, d_halo:-d_halo, d_halo:-d_halo]
        penult = penult[:, d_halo:-d_halo, d_halo:-d_halo]
        d = valid[:, None, None] & (final != penult)
        flags = jnp.stack([
            jnp.stack([d[:, :b, :b].any((1, 2)),
                       d[:, :b, :].any((1, 2)),
                       d[:, :b, -b:].any((1, 2))], 1),
            jnp.stack([d[:, :, :b].any((1, 2)),
                       d.any((1, 2)),
                       d[:, :, -b:].any((1, 2))], 1),
            jnp.stack([d[:, -b:, :b].any((1, 2)),
                       d[:, -b:, :].any((1, 2)),
                       d[:, -b:, -b:].any((1, 2))], 1),
        ], axis=1)
        # Scatter as a fori_loop so XLA aliases the block through the
        # loop carry (one block copy total, not one per tile). `old`
        # slices the RUNNING block, so an invalid (zero-padded) coord
        # that collides with an already-written tile writes back what
        # is there — a no-op.
        def scatter(i, blk):
            cy, cx = coords[i, 0] * t, coords[i, 1] * t
            old = lax.dynamic_slice(blk, (cy, cx), (t, t))
            new = jnp.where(valid[i], final[i], old)
            return lax.dynamic_update_slice(blk, new, (cy, cx))

        newblk = lax.fori_loop(0, kcap, scatter, block)
        live = jnp.zeros((), bool)
        if layout in ("row", "cart"):
            live |= (newblk[:b, :] != 0).any()
            live |= (newblk[-b:, :] != 0).any()
        if layout in ("col", "cart"):
            live |= (newblk[:, :b] != 0).any()
            live |= (newblk[:, -b:] != 0).any()
        return newblk, flags[None], live.reshape(1)

    smapped = mesh_lib.shard_map(
        body, mesh=mesh,
        in_specs=(pspec, coords_spec, nvalid_spec),
        out_specs=(pspec, flags_spec, nvalid_spec),
        check_vma=False)
    return jax.jit(smapped)


class SparseShardedEngine:
    """Advance a SHARDED torus board, stepping only tiles that might
    change — per-round cost proportional to the live area of the whole
    mesh, not the board area of any shard.

    The board is device-resident (sharded by ``layout``); the tile mask
    is host-resident in GLOBAL tile coordinates, and every round is one
    collective dispatch: gather active tiles per shard from the
    exchanged (or zero-sentinel) padded frame, step them ``fuse`` times
    (radius*fuse-deep halos make the round data-complete, amortizing
    the host sync across fuse steps), scatter back, return per-tile
    band flags + a per-shard boundary-live scalar. The per-shard tile
    counts are padded on a pow2 rung ladder (floor 8) so a run compiles
    O(log tiles) programs (x2 for the exchange/skip twin).

    ``engine_stamp``: ``sparse-sharded:<layout>:t<tile>`` while sparse
    rounds ran, ``dense:crossover`` when the active fraction forced
    every round dense, ``dense:sharded`` when the plan is disabled.
    """

    def __init__(self, spec: StencilSpec, board, *, mesh,
                 layout: str = "row", tile: int = 64,
                 crossover: float = 0.5, exchange_skip: bool = True,
                 fuse: int = 16):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding

        if spec.channels != 1:
            raise ValueError(
                f"sparse_sharded: single-channel specs only, "
                f"{spec.name!r} has {spec.channels}")
        board = np.asarray(board, dtype=spec.np_dtype)
        ny, nx = board.shape[-2:]
        py, px = engine.mesh_axes_for(layout, mesh)
        if ny % py or nx % px:
            raise ValueError(
                f"board {(ny, nx)} does not divide mesh "
                f"{dict(mesh.shape)} under layout={layout!r}")
        h, w = ny // py, nx // px
        if h % tile or w % tile:
            raise ValueError(
                f"sparse_sharded: tile {tile} must divide the shard "
                f"{h}x{w}")
        if spec.radius > tile:
            raise ValueError(
                f"sparse_sharded: radius {spec.radius} exceeds tile "
                f"{tile} (one-tile dilation would under-activate)")
        self.spec = spec
        self.mesh = mesh
        self.layout = layout
        self.tile = int(tile)
        self.crossover = float(crossover)
        self.exchange_skip = bool(exchange_skip)
        # Steps per dispatch. The fused halo must stay inside one tile
        # ring (radius * fuse <= tile) so the 3x3 wake flags still name
        # every tile activation can reach in one round.
        self.fuse = max(1, min(int(fuse), self.tile // spec.radius))
        self.shape = (ny, nx)
        self.mesh_axes = (py, px)
        self.shard_shape = (h, w)
        self.plan = plan_sparse_sharded(
            layout, (py, px), (h, w), spec.radius, tile,
            crossover=crossover)
        # Global and per-shard tile grids.
        self.ty, self.tx = ny // tile, nx // tile
        self._mty, self._mtx = h // tile, w // tile
        self._pspec = engine.sharded_pspec(layout, 1)
        self.board = jax.device_put(
            jnp.asarray(board, spec.dtype),
            NamedSharding(mesh, self._pspec))
        # Everything starts active, and the first round exchanges:
        # settledness and dead boundaries are proven, never assumed.
        self.active = np.ones((self.ty, self.tx), dtype=bool)
        self._exchange_needed = True
        self._programs: dict = {}
        self._dense_run = None  # built lazily: crossover may never hit
        self.sparse_steps = 0
        self.dense_steps = 0
        self.settled_steps = 0
        self.tiles_stepped = 0
        self.tiles_skipped = 0
        self.exchange_rounds = 0
        self.exchange_skips = 0
        self._frac_sum = 0.0
        self._frac_n = 0

    # -- observability -----------------------------------------------------
    @property
    def active_frac(self) -> float:
        return float(self.active.mean())

    @property
    def mean_active_frac(self) -> float:
        return self._frac_sum / self._frac_n if self._frac_n else 1.0

    @property
    def engine_stamp(self) -> str:
        if not self.plan.enabled:
            return "dense:sharded"
        if self.dense_steps and not self.sparse_steps:
            return "dense:crossover"
        return self.plan.engine

    def counters(self) -> dict:
        """Bench/ledger sub-object: step mix, skip accounting, and the
        exchange-round/skip split the tests assert a delta on."""
        return {
            "sparse_steps": self.sparse_steps,
            "dense_steps": self.dense_steps,
            "settled_steps": self.settled_steps,
            "tiles_stepped": self.tiles_stepped,
            "tiles_skipped": self.tiles_skipped,
            "exchange_rounds": self.exchange_rounds,
            "exchange_skips": self.exchange_skips,
            "tile": self.tile,
            "fuse": self.fuse,
            "crossover": self.crossover,
            "active_frac": round(self.mean_active_frac, 6),
        }

    def snapshot(self) -> np.ndarray:
        return np.asarray(self.board)

    # -- stepping ----------------------------------------------------------
    def step(self, n: int = 1):
        n = int(n)
        while n > 0:
            f = min(self.fuse, n)
            self._round(f)
            n -= f
        return self.board

    def _round(self, f: int) -> None:
        frac = self.active.mean()
        self._frac_sum += float(frac)
        self._frac_n += 1
        if not self.plan.enabled or frac > self.crossover:
            self._dense_round(f)
            return
        self.sparse_steps += f
        idx = np.argwhere(self.active)
        k = len(idx)
        self.tiles_stepped += k
        self.tiles_skipped += self.ty * self.tx - k
        if k == 0:
            # Fully settled: nothing can change, by construction — no
            # dispatch, no exchange, and the board's boundary liveness
            # is unchanged so the standing exchange flag stays valid.
            self.settled_steps += f
            return
        self._sparse_round(idx, f)

    # -- the sparse collective round ---------------------------------------

    def _bucket(self, idx: np.ndarray):
        """Bucket global active-tile coords by owning shard: returns
        ``(coords, nvalid, per_shard)`` where ``coords`` is
        ``(nshards, kcap, 2)`` int32 LOCAL tile coords (zero-padded),
        ``nvalid`` the per-shard valid counts, and ``per_shard`` the
        host-side global-coord lists in gather order."""
        py, px = self.mesh_axes
        nshards = {"row": py, "col": px, "cart": py * px}[self.layout]
        per_shard: list[list[tuple[int, int]]] = [
            [] for _ in range(nshards)]
        for gy, gx in idx:
            sy, sx = gy // self._mty, gx // self._mtx
            s = {"row": sy, "col": sx, "cart": sy * px + sx}[self.layout]
            per_shard[s].append((int(gy), int(gx)))
        # Rung ladder coarser than sparse.py's: pow2 with a floor of 8.
        # Each rung is a separate shard_map compile, and a rung first
        # reached late in a long run would land its compile inside the
        # timed region — over-padding a handful of 64^2 tile steps is
        # far cheaper than another trace+compile.
        k = max(1, max(len(p) for p in per_shard))
        kcap = 8
        while kcap < k:
            kcap *= 2
        coords = np.zeros((nshards, kcap, 2), np.int32)
        nvalid = np.zeros((nshards,), np.int32)
        for s, tiles in enumerate(per_shard):
            nvalid[s] = len(tiles)
            for i, (gy, gx) in enumerate(tiles):
                coords[s, i] = (gy % self._mty, gx % self._mtx)
        return coords, nvalid, per_shard

    def _sparse_round(self, idx: np.ndarray, f: int) -> None:
        exchange = self._exchange_needed or not self.exchange_skip
        coords, nvalid, per_shard = self._bucket(idx)
        prog = self._program(coords.shape[1], f, exchange)
        self.board, flags, live = prog(self.board, coords, nvalid)
        if exchange:
            self.exchange_rounds += 1
        else:
            self.exchange_skips += 1
        # flags/live are tiny ((nshards, kcap, 3, 3) bools + nshards
        # scalars); fetching them is the host's per-round sync point —
        # one combined fetch, the board itself stays device-resident.
        import jax

        flags, live = jax.device_get((flags, live))
        self._exchange_needed = bool(live.any())
        nxt = np.zeros((self.ty, self.tx), dtype=bool)
        ty, tx = self.ty, self.tx
        for s, tiles in enumerate(per_shard):
            for i, (gy, gx) in enumerate(tiles):
                f = flags[s, i]
                if not f[1, 1]:
                    continue  # tile came back bit-identical: sleeps
                for dy in (-1, 0, 1):
                    for dx in (-1, 0, 1):
                        if f[dy + 1, dx + 1]:
                            nxt[(gy + dy) % ty, (gx + dx) % tx] = True
        self.active = nxt

    def _program(self, kcap: int, f: int, exchange: bool):
        """The jitted shard_map round for one (kcap, fuse, exchange)
        triple — the engine's whole compiled-program space is the
        kcap rung ladder times the exchange/zero-sentinel twin
        (times a tail-fuse rung when ``n % fuse != 0``). Programs are
        cached at MODULE level (``_compiled_round``) so fresh engine
        instances over the same geometry — the bench's honesty bracket
        re-runs, the tuner's per-candidate engines — share compiles."""
        key = (kcap, f, exchange)
        if key not in self._programs:
            self._programs[key] = _compiled_round(
                self.spec, self.mesh, self.layout, self.tile,
                kcap, f, self.spec.radius * self.fuse, exchange)
        return self._programs[key]

    # -- the dense-crossover rung ------------------------------------------

    def _dense_round(self, f: int) -> None:
        import jax

        self.dense_steps += f
        if self._dense_run is None:
            run, _plan_ = engine.make_sharded_runner(
                self.spec, self.mesh, self.layout, self.shape,
                fuse_steps=1)
            ty, tx, t = self.ty, self.tx, self.tile
            diff = jax.jit(lambda a, b: (a != b).reshape(
                ty, t, tx, t).any(axis=(1, 3)))
            self._dense_run = (run, diff)
        run, diff = self._dense_run
        # The mask rebuild diffs the LAST step pair, not first-vs-final
        # — an oscillator whose period divides f would look settled
        # under the cumulative diff (same trap as the fused wake).
        prev = run(self.board, f - 1) if f > 1 else self.board
        new = run(prev, 1)
        changed = np.asarray(diff(new, prev))
        self.board = new
        self.active = _dilate(changed)
        # Conservative: the dense round computed no boundary-live flag.
        self._exchange_needed = True
