"""Stencil spec subsystem: every rule a servable workload.

See ``stencils.spec`` (the declarative :class:`StencilSpec` + registry),
``stencils.engine`` (spec-generated roll / padded / oracle steps),
``stencils.sparse`` (the active-tile engine for mostly-dead boards), and
``stencils.sparse_sharded`` (the same skip logic composed with the
sharded halo exchange — global tile mask, cross-shard activation).
"""

from .engine import (  # noqa: F401
    ENGINE_FAMILIES,
    FFT_MIN_RADIUS,
    aggregate_roll,
    family_allowed,
    family_for_path,
    family_pinned,
    fft_supported,
    offsets,
    oracle_run,
    pallas_batch_supported,
    parity_ok,
    parity_tol_for,
    run_family,
    run_family_batch,
    run_padded_pallas_batch,
    run_roll,
    run_roll_batch,
    separable_supported,
    step_fft,
    step_numpy,
    step_padded,
    step_padded_family,
    step_roll,
    step_sep,
)
from .spec import (  # noqa: F401
    GRAY_SCOTT,
    HEAT,
    LENIA,
    LIFE,
    WIREWORLD,
    StencilSpec,
    get,
    make_lenia,
    names,
    register,
)
from .sparse import ActiveTileEngine  # noqa: F401
from .sparse_sharded import SparseShardedEngine  # noqa: F401
