"""Stencil spec subsystem: every rule a servable workload.

See ``stencils.spec`` (the declarative :class:`StencilSpec` + registry),
``stencils.engine`` (spec-generated roll / padded / oracle steps),
``stencils.sparse`` (the active-tile engine for mostly-dead boards), and
``stencils.sparse_sharded`` (the same skip logic composed with the
sharded halo exchange — global tile mask, cross-shard activation).
"""

from .engine import (  # noqa: F401
    aggregate_roll,
    offsets,
    oracle_run,
    pallas_batch_supported,
    parity_ok,
    run_padded_pallas_batch,
    run_roll,
    run_roll_batch,
    step_numpy,
    step_padded,
    step_roll,
)
from .spec import (  # noqa: F401
    GRAY_SCOTT,
    HEAT,
    LIFE,
    WIREWORLD,
    StencilSpec,
    get,
    names,
    register,
)
from .sparse import ActiveTileEngine  # noqa: F401
from .sparse_sharded import SparseShardedEngine  # noqa: F401
