"""Sparse active-tile stencil engine: skip the settled regions.

The Hashlife insight without the hash: a cell can only change if some
cell within its radius changed last step, so a fixed-size tile whose
radius-wide neighbourhood is settled is guaranteed settled this step.
The engine keeps a boolean per-tile "active" mask — changed tiles, plus
each neighbour whose shared border band (the ``radius``-wide strip,
valid because ``radius <= tile``) actually changed — gathers just the
active tiles (with their radius halos, via modular index arrays — no
full-board pad copy), advances them in one vmapped jitted dispatch, and
scatters the results back. Tiles that came back bit-identical drop out
of the next mask; a glider crossing a tile edge wakes exactly the tile
it is entering through the band check.

When the active fraction exceeds ``crossover`` the sparse bookkeeping
costs more than it saves, so the step falls back to the dense jitted
roll path and rebuilds the mask from the full-board diff — the engine
is never slower than dense by more than the diff, and on mostly-dead
boards it is bounded by the live area instead of the board area (a
scaling axis orthogonal to bit-slicing, which wins on many small DENSE
boards).

The gathered stack's tile count is padded to the next power of two, so
a run compiles O(log max_tiles) programs, not one per active count —
the same discipline as ``serve.batcher.bucket_batch_size``.
"""

from __future__ import annotations

import numpy as np

from . import engine
from .spec import StencilSpec


def _pad_count(n: int) -> int:
    """Next size on the {pow2, 1.5*pow2} ladder (1,2,3,4,6,8,12,16,...):
    O(log max_tiles) compiled stack shapes like pow2 rounding, but at
    most 33% padded waste instead of pow2's 100%."""
    p = 1
    while p < n:
        if p + p // 2 >= n and p >= 2:
            return p + p // 2
        p *= 2
    return p


def _dilate(mask: np.ndarray) -> np.ndarray:
    """8-neighbour dilation with torus wrap (matches the torus board)."""
    out = mask.copy()
    for dy in (-1, 0, 1):
        for dx in (-1, 0, 1):
            if dy or dx:
                out |= np.roll(np.roll(mask, dy, axis=0), dx, axis=1)
    return out


class ActiveTileEngine:
    """Advance a torus board, stepping only tiles that might change.

    ``board`` is host-resident (NumPy); every step is host-driven —
    gather active tiles, one device dispatch, scatter back. That trade
    is deliberate: the workload this engine wins on (huge, mostly-dead
    boards) is exactly the one where shipping a handful of tiles beats
    dispatching the whole board, and the host-side mask is what makes
    the skip decision free.

    ``engine_stamp`` carries provenance for the bench line / sentinel:
    ``sparse:t<tile>`` while the sparse path is winning, or
    ``dense:crossover`` once the active fraction forced the fallback —
    the sentinel ranks ``sparse:* > dense:*`` so a silent flip on the
    same workload flags as a downgrade.
    """

    def __init__(self, spec: StencilSpec, board, *, tile: int = 128,
                 crossover: float = 0.5):
        import jax

        self.spec = spec
        board = np.array(board, dtype=spec.np_dtype)
        if board.shape != spec.board_shape(*board.shape[-2:]):
            raise ValueError(
                f"sparse: board shape {board.shape} does not match "
                f"spec {spec.name!r} (channels={spec.channels})")
        ny, nx = board.shape[-2:]
        if ny % tile or nx % tile:
            raise ValueError(
                f"sparse: tile {tile} must divide the board {ny}x{nx}")
        if spec.radius > tile:
            raise ValueError(
                f"sparse: radius {spec.radius} exceeds tile {tile} "
                "(the one-tile dilation would under-activate)")
        self.board = board
        self.tile = int(tile)
        self.crossover = float(crossover)
        self.ty, self.tx = ny // tile, nx // tile
        # Everything starts active: the first step proves settledness,
        # it is never assumed.
        self.active = np.ones((self.ty, self.tx), dtype=bool)
        self.sparse_steps = 0
        self.dense_steps = 0
        self.tiles_stepped = 0
        self.tiles_skipped = 0
        self._frac_sum = 0.0
        self._frac_n = 0

        r = spec.radius
        self._tile_fn = jax.jit(
            jax.vmap(lambda p: engine.step_padded(spec, p)))
        self._dense_fn = jax.jit(lambda b: engine.step_roll(spec, b))
        # Modular halo index rows per tile coordinate, precomputed once.
        self._rows = [
            np.arange(j * tile - r, (j + 1) * tile + r) % ny
            for j in range(self.ty)]
        self._cols = [
            np.arange(i * tile - r, (i + 1) * tile + r) % nx
            for i in range(self.tx)]

    # -- observability -----------------------------------------------------
    @property
    def active_frac(self) -> float:
        """Current fraction of tiles in the active mask."""
        return float(self.active.mean())

    @property
    def mean_active_frac(self) -> float:
        """Mean active fraction over every step taken so far."""
        return self._frac_sum / self._frac_n if self._frac_n else 1.0

    @property
    def engine_stamp(self) -> str:
        if self.dense_steps and not self.sparse_steps:
            return "dense:crossover"
        return f"sparse:t{self.tile}"

    # -- stepping ----------------------------------------------------------
    def step(self, n: int = 1) -> np.ndarray:
        for _ in range(int(n)):
            self._step_once()
        return self.board

    def _step_once(self) -> None:
        frac = self.active.mean()
        self._frac_sum += float(frac)
        self._frac_n += 1
        if frac > self.crossover:
            self._dense_step()
            return
        self.sparse_steps += 1
        idx = np.argwhere(self.active)
        k = len(idx)
        self.tiles_stepped += k
        self.tiles_skipped += self.ty * self.tx - k
        if k == 0:
            return  # fully settled: nothing can change, by construction
        t, r = self.tile, self.spec.radius
        side = t + 2 * r
        kp = _pad_count(k)
        lead = (self.spec.channels,) if self.spec.channels > 1 else ()
        stack = np.zeros((kp, *lead, side, side), dtype=self.board.dtype)
        for s, (j, i) in enumerate(idx):
            stack[s] = self.board[
                ..., self._rows[j][:, None], self._cols[i][None, :]]
        out = np.asarray(self._tile_fn(stack))
        # Border-band activation: a neighbour tile only needs to wake
        # when changed cells sit within ``radius`` of the shared edge —
        # an oscillator in a tile's interior keeps its 8 neighbours
        # asleep, which is most of the sparse win on scattered debris.
        nxt = np.zeros((self.ty, self.tx), dtype=bool)
        ty, tx = self.ty, self.tx
        for s, (j, i) in enumerate(idx):
            new = out[s]
            sl = (..., slice(j * t, (j + 1) * t), slice(i * t, (i + 1) * t))
            d = new != self.board[sl]
            if self.spec.channels > 1:
                d = d.any(axis=0)
            if not d.any():
                continue
            self.board[sl] = new
            nxt[j, i] = True
            up, dn = (j - 1) % ty, (j + 1) % ty
            lf, rt = (i - 1) % tx, (i + 1) % tx
            if d[:r, :].any():
                nxt[up, i] = True
            if d[-r:, :].any():
                nxt[dn, i] = True
            if d[:, :r].any():
                nxt[j, lf] = True
            if d[:, -r:].any():
                nxt[j, rt] = True
            if d[:r, :r].any():
                nxt[up, lf] = True
            if d[:r, -r:].any():
                nxt[up, rt] = True
            if d[-r:, :r].any():
                nxt[dn, lf] = True
            if d[-r:, -r:].any():
                nxt[dn, rt] = True
        self.active = nxt

    def _dense_step(self) -> None:
        self.dense_steps += 1
        # np.array (copy) — np.asarray of a device array is read-only,
        # and the next sparse step scatters into the board in place.
        out = np.array(self._dense_fn(self.board))
        diff = out != self.board
        if self.spec.channels > 1:
            diff = diff.any(axis=0)
        t = self.tile
        changed = diff.reshape(self.ty, t, self.tx, t).any(axis=(1, 3))
        self.board = out
        self.active = _dilate(changed)

    def counters(self) -> dict:
        """Bench/ledger sub-object: step mix + skip accounting."""
        return {
            "sparse_steps": self.sparse_steps,
            "dense_steps": self.dense_steps,
            "tiles_stepped": self.tiles_stepped,
            "tiles_skipped": self.tiles_skipped,
            "tile": self.tile,
            "crossover": self.crossover,
            "active_frac": round(self.mean_active_frac, 6),
        }
