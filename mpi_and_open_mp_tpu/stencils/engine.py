"""Generic stencil step generators: one spec, every fast path.

Every path here derives from the SAME offset table (nonzero ``weights``
entries in row-major order), so the NumPy oracle and the jitted fast
paths perform the aggregation in the same order — bit-exact for integer
dtypes, reproducibly close for floats (XLA may still fuse/reassociate,
which is why float parity gates use a tight ``allclose`` instead of
``array_equal``; see ``tests/test_stencils.py``).

Paths:

* :func:`step_roll` — torus step via shifts on the last two axes
  (channels ride the leading axis untouched). The radius-1 all-ones box
  (Life's neighbourhood) takes the separable row-sum/col-sum form —
  exactly ``ops.life_ops.life_step_roll``'s shape, 4 shifts instead
  of 8.
* :func:`step_padded` — interior step over a board carrying a
  ``radius``-wide halo on the last two axes; pure slicing, no wrap, so
  it drops straight into shard-local halo blocks and Pallas kernels.
* :func:`step_numpy` — the derived NumPy oracle (plain per-offset roll
  loop; specs may pin an independent ``oracle_step`` instead).
* :func:`run_roll` — jitted ``fori_loop`` chain of :func:`step_roll`
  for benchmarking (n is a runtime scalar: one compile per board shape).
"""

from __future__ import annotations

import functools

import numpy as np

from .spec import BOX3, StencilSpec


@functools.lru_cache(maxsize=None)
def offsets(spec: StencilSpec) -> tuple:
    """Nonzero ``(dy, dx, weight)`` neighbour displacements, row-major.
    A neighbour at displacement ``(dy, dx)`` contributes
    ``weight * board[y + dy, x + dx]`` to the aggregate."""
    r = spec.radius
    out = []
    for j, row in enumerate(spec.weights):
        for i, w in enumerate(row):
            if w:
                out.append((j - r, i - r, w))
    return tuple(out)


def _is_box3(spec: StencilSpec) -> bool:
    return spec.radius == 1 and spec.weights == BOX3


def _shift(field, dy, dx, xp):
    # roll(-dy) moves the value at y+dy into row y (and likewise for x).
    out = field
    if dy:
        out = xp.roll(out, -dy, axis=-2)
    if dx:
        out = xp.roll(out, -dx, axis=-1)
    return out


def aggregate_roll(spec: StencilSpec, board, xp):
    """The weighted neighbour sum of a torus board (last two axes)."""
    field = board if spec.pre is None else spec.pre(board, xp)
    if _is_box3(spec):
        rows = field + xp.roll(field, 1, axis=-2) + xp.roll(field, -1, axis=-2)
        return (rows + xp.roll(rows, 1, axis=-1)
                + xp.roll(rows, -1, axis=-1) - field)
    agg = None
    for dy, dx, w in offsets(spec):
        term = _shift(field, dy, dx, xp)
        if w != 1:
            term = term * w
        agg = term if agg is None else agg + term
    return agg


def step_roll(spec: StencilSpec, board, xp=None):
    """One torus step via rolls; works under numpy or jax.numpy."""
    if xp is None:
        import jax.numpy as xp  # noqa: F811
    return spec.update(board, aggregate_roll(spec, board, xp), xp)


def step_padded(spec: StencilSpec, padded, xp=None):
    """One interior step over a halo-padded block.

    ``padded`` carries a ``spec.radius``-deep halo on the last two axes;
    the result is the updated interior (halo trimmed). Slicing only —
    usable inside Pallas kernels and shard_map bodies unchanged.
    """
    if xp is None:
        import jax.numpy as xp  # noqa: F811
    r = spec.radius
    h = padded.shape[-2] - 2 * r
    w = padded.shape[-1] - 2 * r
    field = padded if spec.pre is None else spec.pre(padded, xp)
    center = padded[..., r:r + h, r:r + w]
    if _is_box3(spec):
        rows = (field[..., 0:h, :] + field[..., 1:h + 1, :]
                + field[..., 2:h + 2, :])
        agg = (rows[..., 0:w] + rows[..., 1:w + 1] + rows[..., 2:w + 2]
               - field[..., 1:h + 1, 1:w + 1])
    else:
        agg = None
        for dy, dx, wt in offsets(spec):
            term = field[..., r + dy:r + dy + h, r + dx:r + dx + w]
            if wt != 1:
                term = term * wt
            agg = term if agg is None else agg + term
    return spec.update(center, agg, xp)


def step_numpy(spec: StencilSpec, board: np.ndarray) -> np.ndarray:
    """The spec's NumPy oracle step (independent ``oracle_step`` when
    the spec pins one, else the derived per-offset roll loop)."""
    board = np.asarray(board, dtype=spec.np_dtype)
    if spec.oracle_step is not None:
        return spec.oracle_step(board)
    field = board if spec.pre is None else spec.pre(board, np)
    agg = None
    for dy, dx, w in offsets(spec):
        term = _shift(field, dy, dx, np)
        if w != 1:
            term = term * w
        agg = term if agg is None else agg + term
    return np.asarray(spec.update(board, agg, np), dtype=spec.np_dtype)


def oracle_run(spec: StencilSpec, board: np.ndarray, n: int) -> np.ndarray:
    out = np.asarray(board, dtype=spec.np_dtype)
    for _ in range(int(n)):
        out = step_numpy(spec, out)
    return out


def parity_ok(spec: StencilSpec, got, want, *, rtol=1e-5, atol=1e-6) -> bool:
    """The per-spec parity predicate: exact for integer dtypes, tight
    allclose for floats (XLA vs NumPy may reassociate float sums)."""
    got = np.asarray(got)
    want = np.asarray(want)
    if got.shape != want.shape:
        return False
    if spec.is_float:
        return bool(np.allclose(got, want, rtol=rtol, atol=atol))
    return bool(np.array_equal(got, want))


@functools.lru_cache(maxsize=None)
def _run_roll_jit(spec: StencilSpec):
    import jax
    import jax.numpy as jnp
    from jax import lax

    def run(board, n):
        return lax.fori_loop(
            0, n, lambda _, b: step_roll(spec, b, jnp), board)

    return jax.jit(run)


def run_roll(spec: StencilSpec, board, n: int):
    """``n`` chained :func:`step_roll` steps as ONE dispatch (jitted
    fori_loop; ``n`` is a runtime scalar so run-length differencing
    reuses a single compiled program per board shape)."""
    return _run_roll_jit(spec)(board, n)


@functools.lru_cache(maxsize=None)
def _run_roll_batch_jit(spec: StencilSpec):
    import jax
    import jax.numpy as jnp
    from jax import lax

    # vmap over the leading stack axis so multi-channel rules (which
    # index channels as center[0]/center[1]) see one board at a time.
    vstep = jax.vmap(lambda b: step_roll(spec, b, jnp))

    def run(stack, n):
        return lax.fori_loop(0, n, lambda _, s: vstep(s), stack)

    return jax.jit(run)


def run_roll_batch(spec: StencilSpec, stack, n: int):
    """``n`` chained torus steps of a STACK of boards as one dispatch —
    the generic serve-layer batch engine (``n`` is a runtime scalar,
    matching the life batch engines' calling convention, so a bucket
    compiles once per stack shape)."""
    return _run_roll_batch_jit(spec)(stack, n)


def pallas_batch_supported(spec: StencilSpec, shape) -> bool:
    """Whether the per-spec Pallas padded kernel can serve a batched
    ``(B, ny, nx)`` stack of this spec: single-channel rules only. The
    kernel rides the stack through the padded block's leading axis, and
    a multi-channel update (which indexes ``center[0]``/``center[1]``)
    would misread that axis as channels — gray_scott stays on the
    vmapped roll engine."""
    return int(spec.channels) == 1 and len(tuple(shape)) == 3


@functools.lru_cache(maxsize=None)
def _run_padded_pallas_batch_jit(spec: StencilSpec):
    import jax
    import jax.numpy as jnp
    from jax import lax

    from mpi_and_open_mp_tpu.ops import pallas_life

    r = spec.radius

    def step(stack):
        padded = jnp.pad(stack, ((0, 0), (r, r), (r, r)), mode="wrap")
        return pallas_life.stencil_step_padded_pallas(spec, padded)

    def run(stack, n):
        return lax.fori_loop(0, n, lambda _, s: step(s), stack)

    return jax.jit(run)


def run_padded_pallas_batch(spec: StencilSpec, stack, n: int):
    """``n`` chained steps of a single-channel stack through the
    spec-generic Pallas padded kernel (``ops.pallas_life.
    stencil_step_padded_pallas``): wrap-pad the halo, one kernel launch
    per step, same runtime-scalar ``n`` contract as
    :func:`run_roll_batch`. Over-VMEM blocks degrade to the compiled
    jnp interior step inside the same loop — the caller never has to
    re-plan. Gate callers on :func:`pallas_batch_supported`."""
    return _run_padded_pallas_batch_jit(spec)(stack, n)


# ------------------------------------------------------- sharded halo steps
#
# The engine-level sharded entry: shard_map halo rounds driven by a
# persistent HaloPlan (parallel.haloplan) — the overlap/sequential
# schedule decision is the PLAN's, derived once per geometry, so the
# tuner, the bench A/B and the model layer all measure the same two
# schedules instead of three ad-hoc code paths.


def _sharded_pspec(layout: str, channels: int):
    """PartitionSpec for a (channels-leading) board under ``layout`` —
    the engine-side twin of ``models.life._layout_spec``."""
    from jax.sharding import PartitionSpec as P

    axes = {"row": ("y", None), "col": (None, "x"),
            "cart": ("y", "x")}[layout]
    return P(None, *axes) if channels > 1 else P(*axes)


#: Public alias — the sparse-sharded engine and the tuner place boards
#: with the same spec the sharded runner uses, by name.
sharded_pspec = _sharded_pspec


def mesh_axes_for(layout: str, mesh) -> tuple[int, int]:
    """(py, px) shard counts per board axis under ``layout``."""
    py = mesh.shape.get("y", 1) if layout in ("row", "cart") else 1
    px = mesh.shape.get("x", 1) if layout in ("col", "cart") else 1
    return py, px


def fused_steps_valid(spec: StencilSpec, shard_shape: tuple[int, int],
                      fuse_steps: int) -> bool:
    """Whether ``fuse_steps`` legal-fuses on this shard: the halo depth
    ``fuse_steps * radius`` cannot exceed the smallest shard extent (a
    halo deeper than the shard it pads would wrap a neighbour's
    neighbour)."""
    return fuse_steps * spec.radius <= min(shard_shape)


def make_sharded_runner(spec: StencilSpec, mesh, layout: str,
                        shape: tuple[int, int], *, fuse_steps: int = 1,
                        boundary_steps: int | None = None,
                        overlap: bool | None = None):
    """Build ``(run, plan)`` for a sharded board: ``run(board, n)``
    advances ``n`` torus steps via plan-scheduled shard_map halo rounds.

    ``overlap=None`` lets the plan decide (geometry + the
    ``MOMP_HALO_OVERLAP`` kill switch); ``False`` forces the sequential
    schedule — the A/B baseline leg — and stamps ``why`` accordingly.
    ``boundary_steps`` (default: coupled) partitions each round's
    boundary into shallower per-edge sub-exchanges; it must divide
    ``fuse_steps``. ``run`` is jit-cached per static ``n`` (remainder
    rounds get their own smaller-depth plan — coupled boundary, and
    possibly a legal sequential degrade — even when the main rounds
    overlap partitioned).
    """
    import dataclasses as _dc
    import functools as _ft

    import jax
    import jax.numpy as jnp
    from jax import lax

    from mpi_and_open_mp_tpu.parallel import haloplan, mesh as mesh_lib

    ny, nx = shape
    py, px = mesh_axes_for(layout, mesh)
    if ny % py or nx % px:
        raise ValueError(
            f"board {shape} does not divide mesh {dict(mesh.shape)} "
            f"under layout={layout!r}")
    shard = (ny // py, nx // px)
    if not fused_steps_valid(spec, shard, fuse_steps):
        raise ValueError(
            f"fuse_steps={fuse_steps} x radius {spec.radius} exceeds "
            f"shard {shard}")

    def plan_for(k: int) -> "haloplan.HaloPlan":
        bs = boundary_steps if k == fuse_steps else None
        p = haloplan.plan_halo(layout, (py, px), shard, spec.radius, k,
                               boundary_steps=bs,
                               channels=spec.channels)
        if overlap is False and p.overlap:
            p = _dc.replace(p, overlap=False, engine="seq:halo",
                            why="forced sequential (A/B baseline)")
        return p

    plan = plan_for(fuse_steps)
    pspec = _sharded_pspec(layout, spec.channels)

    def step_fn(padded):
        return step_padded(spec, padded, jnp)

    def make_smapped(k: int):
        pk = plan_for(k)
        return mesh_lib.shard_map(
            lambda b: haloplan.fused_step(pk, step_fn, b),
            mesh=mesh, in_specs=pspec, out_specs=pspec, check_vma=False)

    smapped_k = make_smapped(fuse_steps)
    smapped_cache = {fuse_steps: smapped_k}

    @_ft.partial(jax.jit, static_argnums=1)
    def run(board, n):
        rounds, rem = divmod(n, fuse_steps)
        board = lax.fori_loop(0, rounds, lambda _, b: smapped_k(b), board)
        if rem:
            if rem not in smapped_cache:
                smapped_cache[rem] = make_smapped(rem)
            board = smapped_cache[rem](board)
        return board

    return run, plan


def run_sharded(spec: StencilSpec, board, n: int, *, mesh,
                layout: str = "row", fuse_steps: int = 1,
                boundary_steps: int | None = None,
                overlap: bool | None = None):
    """Advance ``n`` sharded steps under a ``halo.overlap`` /
    ``halo.seq`` trace span (host-level: the span brackets dispatch
    through completion; schedule hooks never enter the jitted program).
    Places the board on the mesh if the caller has not. Returns the
    advanced board; the plan rides on ``run_sharded.last_plan`` for
    provenance stamping."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    from mpi_and_open_mp_tpu.obs import trace
    from mpi_and_open_mp_tpu.utils.timing import anchor_sync

    run, plan = make_sharded_runner(
        spec, mesh, layout, tuple(board.shape[-2:]),
        fuse_steps=fuse_steps, boundary_steps=boundary_steps,
        overlap=overlap)
    run_sharded.last_plan = plan
    sharding = NamedSharding(mesh, _sharded_pspec(layout, spec.channels))
    board = jax.device_put(jnp.asarray(board, spec.dtype), sharding)
    name = "halo.overlap" if plan.overlap else "halo.seq"
    with trace.span(name, engine=plan.engine, layout=layout,
                    workload=spec.name, steps=int(n),
                    fuse_steps=int(fuse_steps)):
        out = run(board, int(n))
        anchor_sync(out)
    return out


run_sharded.last_plan = None
