"""Generic stencil step generators: one spec, every fast path.

Every path here derives from the SAME offset table (nonzero ``weights``
entries in row-major order), so the NumPy oracle and the jitted fast
paths perform the aggregation in the same order — bit-exact for integer
dtypes, reproducibly close for floats (XLA may still fuse/reassociate,
which is why float parity gates use a tight ``allclose`` instead of
``array_equal``; see ``tests/test_stencils.py``).

Paths:

* :func:`step_roll` — torus step via shifts on the last two axes
  (channels ride the leading axis untouched). The radius-1 all-ones box
  (Life's neighbourhood) takes the separable row-sum/col-sum form —
  exactly ``ops.life_ops.life_step_roll``'s shape, 4 shifts instead
  of 8.
* :func:`step_padded` — interior step over a board carrying a
  ``radius``-wide halo on the last two axes; pure slicing, no wrap, so
  it drops straight into shard-local halo blocks and Pallas kernels.
* :func:`step_numpy` — the derived NumPy oracle (plain per-offset roll
  loop; specs may pin an independent ``oracle_step`` instead).
* :func:`run_roll` — jitted ``fori_loop`` chain of :func:`step_roll`
  for benchmarking (n is a runtime scalar: one compile per board shape).

Engine families (PR 20): every path above walks the same
``(2r+1)^2 - 1`` offset table — O(r^2) work per cell. Two families
restructure the aggregation itself for wide-radius float kernels:

* ``sep`` (:func:`step_sep` / :func:`step_padded_sep`) — the weight
  table factors into ``rank`` row x col passes (``spec.separable_rank``,
  SVD-exact); O(rank * r) rolls per cell. Exact when the factorization
  residual is zero; REFUSED (ValueError) otherwise.
* ``fft`` (:func:`step_fft` / :func:`step_padded_fft`) — the torus
  aggregate is a circular convolution, computed via ``rfft2`` with a
  cached kernel transform; O(log n) per cell, radius-independent. Float
  only, periodic boundary native. The parity GATE owns the float
  tolerance (:func:`parity_tol_for`); the engine itself never rounds.

``MOMP_ENGINE_FAMILY`` pins one family (offset|sep|fft) — the offset
walk always stays available as the safety fallback.
"""

from __future__ import annotations

import functools
import os

import numpy as np

from .spec import BOX3, StencilSpec, _separable_factors


@functools.lru_cache(maxsize=None)
def offsets(spec: StencilSpec) -> tuple:
    """Nonzero ``(dy, dx, weight)`` neighbour displacements, row-major.
    A neighbour at displacement ``(dy, dx)`` contributes
    ``weight * board[y + dy, x + dx]`` to the aggregate."""
    r = spec.radius
    out = []
    for j, row in enumerate(spec.weights):
        for i, w in enumerate(row):
            if w:
                out.append((j - r, i - r, w))
    return tuple(out)


def _is_box3(spec: StencilSpec) -> bool:
    return spec.radius == 1 and spec.weights == BOX3


def _shift(field, dy, dx, xp):
    # roll(-dy) moves the value at y+dy into row y (and likewise for x).
    out = field
    if dy:
        out = xp.roll(out, -dy, axis=-2)
    if dx:
        out = xp.roll(out, -dx, axis=-1)
    return out


def aggregate_roll(spec: StencilSpec, board, xp):
    """The weighted neighbour sum of a torus board (last two axes)."""
    field = board if spec.pre is None else spec.pre(board, xp)
    if _is_box3(spec):
        rows = field + xp.roll(field, 1, axis=-2) + xp.roll(field, -1, axis=-2)
        return (rows + xp.roll(rows, 1, axis=-1)
                + xp.roll(rows, -1, axis=-1) - field)
    agg = None
    for dy, dx, w in offsets(spec):
        term = _shift(field, dy, dx, xp)
        if w != 1:
            term = term * w
        agg = term if agg is None else agg + term
    return agg


def step_roll(spec: StencilSpec, board, xp=None):
    """One torus step via rolls; works under numpy or jax.numpy."""
    if xp is None:
        import jax.numpy as xp  # noqa: F811
    return spec.update(board, aggregate_roll(spec, board, xp), xp)


def step_padded(spec: StencilSpec, padded, xp=None):
    """One interior step over a halo-padded block.

    ``padded`` carries a ``spec.radius``-deep halo on the last two axes;
    the result is the updated interior (halo trimmed). Slicing only —
    usable inside Pallas kernels and shard_map bodies unchanged.
    """
    if xp is None:
        import jax.numpy as xp  # noqa: F811
    r = spec.radius
    h = padded.shape[-2] - 2 * r
    w = padded.shape[-1] - 2 * r
    field = padded if spec.pre is None else spec.pre(padded, xp)
    center = padded[..., r:r + h, r:r + w]
    if _is_box3(spec):
        rows = (field[..., 0:h, :] + field[..., 1:h + 1, :]
                + field[..., 2:h + 2, :])
        agg = (rows[..., 0:w] + rows[..., 1:w + 1] + rows[..., 2:w + 2]
               - field[..., 1:h + 1, 1:w + 1])
    else:
        agg = None
        for dy, dx, wt in offsets(spec):
            term = field[..., r + dy:r + dy + h, r + dx:r + dx + w]
            if wt != 1:
                term = term * wt
            agg = term if agg is None else agg + term
    return spec.update(center, agg, xp)


def step_numpy(spec: StencilSpec, board: np.ndarray) -> np.ndarray:
    """The spec's NumPy oracle step (independent ``oracle_step`` when
    the spec pins one, else the derived per-offset roll loop)."""
    board = np.asarray(board, dtype=spec.np_dtype)
    if spec.oracle_step is not None:
        return spec.oracle_step(board)
    field = board if spec.pre is None else spec.pre(board, np)
    agg = None
    for dy, dx, w in offsets(spec):
        term = _shift(field, dy, dx, np)
        if w != 1:
            term = term * w
        agg = term if agg is None else agg + term
    return np.asarray(spec.update(board, agg, np), dtype=spec.np_dtype)


def oracle_run(spec: StencilSpec, board: np.ndarray, n: int) -> np.ndarray:
    out = np.asarray(board, dtype=spec.np_dtype)
    for _ in range(int(n)):
        out = step_numpy(spec, out)
    return out


def parity_ok(spec: StencilSpec, got, want, *, rtol=1e-5, atol=1e-6) -> bool:
    """The per-spec parity predicate: exact for integer dtypes, tight
    allclose for floats (XLA vs NumPy may reassociate float sums)."""
    got = np.asarray(got)
    want = np.asarray(want)
    if got.shape != want.shape:
        return False
    if spec.is_float:
        return bool(np.allclose(got, want, rtol=rtol, atol=atol))
    return bool(np.array_equal(got, want))


@functools.lru_cache(maxsize=None)
def _run_roll_jit(spec: StencilSpec):
    import jax
    import jax.numpy as jnp
    from jax import lax

    def run(board, n):
        return lax.fori_loop(
            0, n, lambda _, b: step_roll(spec, b, jnp), board)

    return jax.jit(run)


def run_roll(spec: StencilSpec, board, n: int):
    """``n`` chained :func:`step_roll` steps as ONE dispatch (jitted
    fori_loop; ``n`` is a runtime scalar so run-length differencing
    reuses a single compiled program per board shape)."""
    return _run_roll_jit(spec)(board, n)


@functools.lru_cache(maxsize=None)
def _run_roll_batch_jit(spec: StencilSpec):
    import jax
    import jax.numpy as jnp
    from jax import lax

    # vmap over the leading stack axis so multi-channel rules (which
    # index channels as center[0]/center[1]) see one board at a time.
    vstep = jax.vmap(lambda b: step_roll(spec, b, jnp))

    def run(stack, n):
        return lax.fori_loop(0, n, lambda _, s: vstep(s), stack)

    return jax.jit(run)


def run_roll_batch(spec: StencilSpec, stack, n: int):
    """``n`` chained torus steps of a STACK of boards as one dispatch —
    the generic serve-layer batch engine (``n`` is a runtime scalar,
    matching the life batch engines' calling convention, so a bucket
    compiles once per stack shape)."""
    return _run_roll_batch_jit(spec)(stack, n)


def pallas_batch_supported(spec: StencilSpec, shape) -> bool:
    """Whether the per-spec Pallas padded kernel can serve a batched
    ``(B, ny, nx)`` stack of this spec: single-channel rules only. The
    kernel rides the stack through the padded block's leading axis, and
    a multi-channel update (which indexes ``center[0]``/``center[1]``)
    would misread that axis as channels — gray_scott stays on the
    vmapped roll engine."""
    return int(spec.channels) == 1 and len(tuple(shape)) == 3


@functools.lru_cache(maxsize=None)
def _run_padded_pallas_batch_jit(spec: StencilSpec):
    import jax
    import jax.numpy as jnp
    from jax import lax

    from mpi_and_open_mp_tpu.ops import pallas_life

    r = spec.radius

    def step(stack):
        padded = jnp.pad(stack, ((0, 0), (r, r), (r, r)), mode="wrap")
        return pallas_life.stencil_step_padded_pallas(spec, padded)

    def run(stack, n):
        return lax.fori_loop(0, n, lambda _, s: step(s), stack)

    return jax.jit(run)


def run_padded_pallas_batch(spec: StencilSpec, stack, n: int):
    """``n`` chained steps of a single-channel stack through the
    spec-generic Pallas padded kernel (``ops.pallas_life.
    stencil_step_padded_pallas``): wrap-pad the halo, one kernel launch
    per step, same runtime-scalar ``n`` contract as
    :func:`run_roll_batch`. Over-VMEM blocks degrade to the compiled
    jnp interior step inside the same loop — the caller never has to
    re-plan. Gate callers on :func:`pallas_batch_supported`."""
    return _run_padded_pallas_batch_jit(spec)(stack, n)


# ------------------------------------------------------- sharded halo steps
#
# The engine-level sharded entry: shard_map halo rounds driven by a
# persistent HaloPlan (parallel.haloplan) — the overlap/sequential
# schedule decision is the PLAN's, derived once per geometry, so the
# tuner, the bench A/B and the model layer all measure the same two
# schedules instead of three ad-hoc code paths.


def _sharded_pspec(layout: str, channels: int):
    """PartitionSpec for a (channels-leading) board under ``layout`` —
    the engine-side twin of ``models.life._layout_spec``."""
    from jax.sharding import PartitionSpec as P

    axes = {"row": ("y", None), "col": (None, "x"),
            "cart": ("y", "x")}[layout]
    return P(None, *axes) if channels > 1 else P(*axes)


#: Public alias — the sparse-sharded engine and the tuner place boards
#: with the same spec the sharded runner uses, by name.
sharded_pspec = _sharded_pspec


def mesh_axes_for(layout: str, mesh) -> tuple[int, int]:
    """(py, px) shard counts per board axis under ``layout``."""
    py = mesh.shape.get("y", 1) if layout in ("row", "cart") else 1
    px = mesh.shape.get("x", 1) if layout in ("col", "cart") else 1
    return py, px


def fused_steps_valid(spec: StencilSpec, shard_shape: tuple[int, int],
                      fuse_steps: int) -> bool:
    """Whether ``fuse_steps`` legal-fuses on this shard: the halo depth
    ``fuse_steps * radius`` cannot exceed the smallest shard extent (a
    halo deeper than the shard it pads would wrap a neighbour's
    neighbour)."""
    return fuse_steps * spec.radius <= min(shard_shape)


def make_sharded_runner(spec: StencilSpec, mesh, layout: str,
                        shape: tuple[int, int], *, fuse_steps: int = 1,
                        boundary_steps: int | None = None,
                        overlap: bool | None = None,
                        family: str = "offset"):
    """Build ``(run, plan)`` for a sharded board: ``run(board, n)``
    advances ``n`` torus steps via plan-scheduled shard_map halo rounds.

    ``overlap=None`` lets the plan decide (geometry + the
    ``MOMP_HALO_OVERLAP`` kill switch); ``False`` forces the sequential
    schedule — the A/B baseline leg — and stamps ``why`` accordingly.
    ``boundary_steps`` (default: coupled) partitions each round's
    boundary into shallower per-edge sub-exchanges; it must divide
    ``fuse_steps``. ``family`` picks the per-shard aggregation engine
    (:func:`step_padded_family`) — the halo plan itself is family-blind:
    every family consumes the same ``radius``-deep ghosts. ``run`` is
    jit-cached per static ``n`` (remainder rounds get their own
    smaller-depth plan — coupled boundary, and possibly a legal
    sequential degrade — even when the main rounds overlap partitioned).
    """
    import dataclasses as _dc
    import functools as _ft

    import jax
    import jax.numpy as jnp
    from jax import lax

    from mpi_and_open_mp_tpu.parallel import haloplan, mesh as mesh_lib

    if family == "sep":
        _require_sep(spec)
    elif family == "fft":
        _require_fft(spec)
    elif family != "offset":
        raise ValueError(f"unknown engine family {family!r}; "
                         f"expected one of {ENGINE_FAMILIES}")
    ny, nx = shape
    py, px = mesh_axes_for(layout, mesh)
    if ny % py or nx % px:
        raise ValueError(
            f"board {shape} does not divide mesh {dict(mesh.shape)} "
            f"under layout={layout!r}")
    shard = (ny // py, nx // px)
    if not fused_steps_valid(spec, shard, fuse_steps):
        raise ValueError(
            f"fuse_steps={fuse_steps} x radius {spec.radius} exceeds "
            f"shard {shard}")

    def plan_for(k: int) -> "haloplan.HaloPlan":
        bs = boundary_steps if k == fuse_steps else None
        p = haloplan.plan_halo(layout, (py, px), shard, spec.radius, k,
                               boundary_steps=bs,
                               channels=spec.channels)
        if overlap is False and p.overlap:
            p = _dc.replace(p, overlap=False, engine="seq:halo",
                            why="forced sequential (A/B baseline)")
        return p

    plan = plan_for(fuse_steps)
    pspec = _sharded_pspec(layout, spec.channels)

    def step_fn(padded):
        return step_padded_family(spec, padded, family, jnp)

    def make_smapped(k: int):
        pk = plan_for(k)
        return mesh_lib.shard_map(
            lambda b: haloplan.fused_step(pk, step_fn, b),
            mesh=mesh, in_specs=pspec, out_specs=pspec, check_vma=False)

    smapped_k = make_smapped(fuse_steps)
    smapped_cache = {fuse_steps: smapped_k}

    @_ft.partial(jax.jit, static_argnums=1)
    def run(board, n):
        rounds, rem = divmod(n, fuse_steps)
        board = lax.fori_loop(0, rounds, lambda _, b: smapped_k(b), board)
        if rem:
            if rem not in smapped_cache:
                smapped_cache[rem] = make_smapped(rem)
            board = smapped_cache[rem](board)
        return board

    return run, plan


def run_sharded(spec: StencilSpec, board, n: int, *, mesh,
                layout: str = "row", fuse_steps: int = 1,
                boundary_steps: int | None = None,
                overlap: bool | None = None, family: str = "offset"):
    """Advance ``n`` sharded steps under a ``halo.overlap`` /
    ``halo.seq`` trace span (host-level: the span brackets dispatch
    through completion; schedule hooks never enter the jitted program).
    Places the board on the mesh if the caller has not. Returns the
    advanced board; the plan rides on ``run_sharded.last_plan`` for
    provenance stamping."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    from mpi_and_open_mp_tpu.obs import trace
    from mpi_and_open_mp_tpu.utils.timing import anchor_sync

    run, plan = make_sharded_runner(
        spec, mesh, layout, tuple(board.shape[-2:]),
        fuse_steps=fuse_steps, boundary_steps=boundary_steps,
        overlap=overlap, family=family)
    run_sharded.last_plan = plan
    sharding = NamedSharding(mesh, _sharded_pspec(layout, spec.channels))
    board = jax.device_put(jnp.asarray(board, spec.dtype), sharding)
    name = "halo.overlap" if plan.overlap else "halo.seq"
    with trace.span(name, engine=plan.engine, layout=layout,
                    workload=spec.name, steps=int(n),
                    fuse_steps=int(fuse_steps), family=family):
        out = run(board, int(n))
        anchor_sync(out)
    return out


run_sharded.last_plan = None


# --------------------------------------------------------- engine families
#
# PR 20: the first aggregation paths whose cost model is NOT the offset
# table. Everything above this line walks (2r+1)^2 - 1 offsets; the
# separable family walks 2 * rank * (2r+1) row/col passes and the FFT
# family is radius-independent. The tuner races all three; the plan
# store persists the winner; MOMP_ENGINE_FAMILY pins one for triage.

#: Closed vocabulary — ledger keys, sentinel provenance and the bench
#: crossover table all speak these three names.
ENGINE_FAMILIES = ("offset", "sep", "fft")

#: Below this radius the FFT's setup constant cannot win — the kernel
#: transform multiply costs the same at radius 1 as radius 16, so the
#: legality gate keeps narrow specs off the candidate list entirely.
FFT_MIN_RADIUS = 4

#: Kill switch: pin one family (offset|sep|fft). The offset walk is
#: always allowed regardless — pinning selects a family, it never
#: removes the safety fallback.
ENV_FAMILY = "MOMP_ENGINE_FAMILY"

#: Gate-owned parity tolerances per family. The ENGINES are exact (sep)
#: or correctly-rounded-transform (fft); what differs is how float32
#: noise amplifies through the update over a parity window, so the GATE
#: — not the engine — owns the slack. offset keeps parity_ok's default.
_FAMILY_TOL = {
    "offset": {},
    "sep": {"rtol": 1e-4, "atol": 1e-5},
    "fft": {"rtol": 1e-3, "atol": 1e-4},
}


def parity_tol_for(family: str) -> dict:
    """kwargs for :func:`parity_ok` when gating ``family`` output."""
    if family not in ENGINE_FAMILIES:
        raise ValueError(f"unknown engine family {family!r}; "
                         f"expected one of {ENGINE_FAMILIES}")
    return dict(_FAMILY_TOL[family])


def family_pinned() -> str | None:
    """The ``MOMP_ENGINE_FAMILY`` pin, validated; None when unset."""
    v = os.environ.get(ENV_FAMILY, "").strip()
    if not v:
        return None
    if v not in ENGINE_FAMILIES:
        raise ValueError(
            f"{ENV_FAMILY}={v!r}: expected one of {ENGINE_FAMILIES}")
    return v


def family_allowed(family: str) -> bool:
    """Whether ``family`` may be enumerated/served under the pin.
    ``offset`` is always allowed — the pin narrows, never strands."""
    pin = family_pinned()
    return pin is None or family == pin or family == "offset"


def family_for_path(path: str) -> str:
    """Engine family of a tuner/plan path string (``stencil:sep`` ->
    ``sep``; everything else is the offset walk)."""
    if path.endswith(":sep"):
        return "sep"
    if path.endswith(":fft"):
        return "fft"
    return "offset"


def separable_supported(spec: StencilSpec) -> bool:
    """Whether the sep family can serve this spec exactly (the weight
    table factors at rank <= radius with zero residual)."""
    return spec.separable_rank is not None


def fft_supported(spec: StencilSpec) -> bool:
    """FFT legality: float dtype (the transform is real-to-complex),
    native periodic boundary, and radius past the setup constant."""
    return (spec.is_float and spec.boundary == "torus"
            and spec.radius >= FFT_MIN_RADIUS)


@functools.lru_cache(maxsize=None)
def _sep_factors(spec: StencilSpec):
    """The spec's row x col factor pairs as plain-float tuples (weak
    scalars: multiplying a float32 field keeps float32 under both
    numpy and jax.numpy), or None when the table does not factor."""
    f = _separable_factors(spec.weights, spec.radius)
    if f is None:
        return None
    return tuple((tuple(float(x) for x in u), tuple(float(x) for x in v))
                 for u, v in f)


def _require_sep(spec: StencilSpec):
    facs = _sep_factors(spec)
    if facs is None:
        raise ValueError(
            f"stencil {spec.name!r}: weights do not factor at rank <= "
            f"radius ({spec.radius}); separable family refused")
    return facs


def _require_fft(spec: StencilSpec):
    if not spec.is_float:
        raise ValueError(
            f"stencil {spec.name!r}: fft family needs a float dtype, "
            f"got {spec.dtype}")
    if spec.boundary != "torus":
        raise ValueError(
            f"stencil {spec.name!r}: fft family is periodic-native; "
            f"boundary {spec.boundary!r} unsupported")


def aggregate_sep(spec: StencilSpec, board, xp):
    """The torus neighbour sum as ``rank`` row-pass x col-pass sweeps:
    ``agg = sum_k (sum_j u_k[j] roll_y) conv (sum_i v_k[i] roll_x)`` —
    2 * rank * (2r+1) rolls instead of (2r+1)^2 - 1."""
    facs = _require_sep(spec)
    field = board if spec.pre is None else spec.pre(board, xp)
    r = spec.radius
    agg = None
    for u, v in facs:
        rows = None
        for j, uw in enumerate(u):
            if not uw:
                continue
            term = xp.roll(field, r - j, axis=-2) if j != r else field
            if uw != 1:
                term = term * uw
            rows = term if rows is None else rows + term
        part = None
        for i, vw in enumerate(v):
            if not vw:
                continue
            term = xp.roll(rows, r - i, axis=-1) if i != r else rows
            if vw != 1:
                term = term * vw
            part = term if part is None else part + term
        agg = part if agg is None else agg + part
    return agg


def step_sep(spec: StencilSpec, board, xp=None):
    """One torus step via the separable family; raises ValueError on
    non-factorizable weights (the refusal is the contract — a silent
    low-rank APPROXIMATION would poison every parity gate above it)."""
    if xp is None:
        import jax.numpy as xp  # noqa: F811
    return spec.update(board, aggregate_sep(spec, board, xp), xp)


@functools.lru_cache(maxsize=None)
def _fft_kernel_rfft(spec: StencilSpec, ny: int, nx: int):
    """rfft2 of the spec's kernel image on an ``ny x nx`` torus. The
    aggregate is a cross-correlation, so the convolution kernel is the
    offset table point-reflected: ``k[(-dy) % ny, (-dx) % nx] = w``
    (``+=``: on boards narrower than the table, wrapped taps pile up
    exactly like the roll path wraps them). complex64 so float32
    pipelines stay float32 end to end."""
    k = np.zeros((ny, nx), np.float64)
    for dy, dx, w in offsets(spec):
        k[(-dy) % ny, (-dx) % nx] += w
    return np.fft.rfft2(k).astype(np.complex64)


def step_fft(spec: StencilSpec, board, xp=None):
    """One torus step via the FFT family: rfft2 of the field times the
    cached kernel transform, inverse-transformed back. Works under
    numpy and jax.numpy; float specs only (refused otherwise)."""
    if xp is None:
        import jax.numpy as xp  # noqa: F811
    _require_fft(spec)
    field = board if spec.pre is None else spec.pre(board, xp)
    ny, nx = int(field.shape[-2]), int(field.shape[-1])
    kf = _fft_kernel_rfft(spec, ny, nx)
    agg = xp.fft.irfft2(xp.fft.rfft2(field) * kf, s=(ny, nx))
    agg = agg.astype(board.dtype)
    return spec.update(board, agg, xp)


def step_padded_sep(spec: StencilSpec, padded, xp=None):
    """Interior separable step over a halo-padded block (slicing only,
    same contract as :func:`step_padded`): row passes slice ``[j:j+h]``,
    col passes slice ``[i:i+w]`` — drops into the PR 15 halo plans with
    ``radius``-deep ghosts unchanged."""
    if xp is None:
        import jax.numpy as xp  # noqa: F811
    facs = _require_sep(spec)
    r = spec.radius
    h = padded.shape[-2] - 2 * r
    w = padded.shape[-1] - 2 * r
    field = padded if spec.pre is None else spec.pre(padded, xp)
    center = padded[..., r:r + h, r:r + w]
    agg = None
    for u, v in facs:
        rows = None
        for j, uw in enumerate(u):
            if not uw:
                continue
            term = field[..., j:j + h, :]
            if uw != 1:
                term = term * uw
            rows = term if rows is None else rows + term
        part = None
        for i, vw in enumerate(v):
            if not vw:
                continue
            term = rows[..., i:i + w]
            if vw != 1:
                term = term * vw
            part = term if part is None else part + term
        agg = part if agg is None else agg + part
    return spec.update(center, agg, xp)


def step_padded_fft(spec: StencilSpec, padded, xp=None):
    """Interior FFT step over a halo-padded block: circular convolution
    on the PADDED extent, interior crop. For output rows ``y`` in
    ``[r, r+h)`` and taps ``dy`` in ``[-r, r]``, ``y + dy`` never wraps
    the padded block — the circular result equals the linear gather
    exactly, so halo semantics match :func:`step_padded` bit-for-float."""
    if xp is None:
        import jax.numpy as xp  # noqa: F811
    _require_fft(spec)
    r = spec.radius
    h = padded.shape[-2] - 2 * r
    w = padded.shape[-1] - 2 * r
    H, W = int(padded.shape[-2]), int(padded.shape[-1])
    field = padded if spec.pre is None else spec.pre(padded, xp)
    kf = _fft_kernel_rfft(spec, H, W)
    full = xp.fft.irfft2(xp.fft.rfft2(field) * kf, s=(H, W))
    agg = full[..., r:r + h, r:r + w].astype(padded.dtype)
    center = padded[..., r:r + h, r:r + w]
    return spec.update(center, agg, xp)


def step_family(spec: StencilSpec, board, family: str = "offset",
                xp=None):
    """One torus step through the named engine family."""
    if family == "offset":
        return step_roll(spec, board, xp)
    if family == "sep":
        return step_sep(spec, board, xp)
    if family == "fft":
        return step_fft(spec, board, xp)
    raise ValueError(f"unknown engine family {family!r}; "
                     f"expected one of {ENGINE_FAMILIES}")


def step_padded_family(spec: StencilSpec, padded, family: str = "offset",
                       xp=None):
    """One interior halo-padded step through the named engine family."""
    if family == "offset":
        return step_padded(spec, padded, xp)
    if family == "sep":
        return step_padded_sep(spec, padded, xp)
    if family == "fft":
        return step_padded_fft(spec, padded, xp)
    raise ValueError(f"unknown engine family {family!r}; "
                     f"expected one of {ENGINE_FAMILIES}")


@functools.lru_cache(maxsize=None)
def _run_family_jit(spec: StencilSpec, family: str):
    import jax
    import jax.numpy as jnp
    from jax import lax

    def run(board, n):
        return lax.fori_loop(
            0, n, lambda _, b: step_family(spec, b, family, jnp), board)

    return jax.jit(run)


def run_family(spec: StencilSpec, board, n: int, family: str = "offset"):
    """``n`` chained steps of one engine family as ONE dispatch — the
    family twin of :func:`run_roll` (same runtime-scalar ``n``, same
    chain-differencing contract). Refusals (non-factorizable sep, int
    fft) raise eagerly, before any compile."""
    if family == "offset":
        return run_roll(spec, board, n)
    if family == "sep":
        _require_sep(spec)
    elif family == "fft":
        _require_fft(spec)
    else:
        raise ValueError(f"unknown engine family {family!r}; "
                         f"expected one of {ENGINE_FAMILIES}")
    return _run_family_jit(spec, family)(board, n)


@functools.lru_cache(maxsize=None)
def _run_family_batch_jit(spec: StencilSpec, family: str):
    import jax
    import jax.numpy as jnp
    from jax import lax

    vstep = jax.vmap(lambda b: step_family(spec, b, family, jnp))

    def run(stack, n):
        return lax.fori_loop(0, n, lambda _, s: vstep(s), stack)

    return jax.jit(run)


def run_family_batch(spec: StencilSpec, stack, n: int,
                     family: str = "offset"):
    """Batched :func:`run_family` — the serve-layer engine behind the
    ``batch:stencil-sep``/``batch:stencil-fft`` rungs, same calling
    convention as :func:`run_roll_batch`."""
    if family == "offset":
        return run_roll_batch(spec, stack, n)
    if family == "sep":
        _require_sep(spec)
    elif family == "fft":
        _require_fft(spec)
    else:
        raise ValueError(f"unknown engine family {family!r}; "
                         f"expected one of {ENGINE_FAMILIES}")
    return _run_family_batch_jit(spec, family)(stack, n)
