"""Declarative stencil specifications + the workload registry.

The reference repo is ONE hard-coded rule (Conway's Life on a torus);
every fast path in this repo — roll, padded-shard, Pallas VMEM, halo
exchange — was welded to it. A :class:`StencilSpec` factors the rule
out: neighborhood weights (radius), cell dtype, channel count, boundary,
and a pure ``update(center, neighbor_agg, xp) -> next`` function. The
generic engine (``stencils.engine``) generates the roll / padded / Pallas
step from any spec; the NumPy oracle for parity gating comes from the
same offset table (or, for ``life``, the historical independent oracle
``ops.life_ops.life_step_numpy`` — the generic path must stay bit-exact
against it, not against itself).

``update`` receives ``xp`` — ``numpy`` or ``jax.numpy`` — so one rule
body serves both the oracle and every jitted fast path (``xp.stack``,
``xp.where`` and friends resolve to whichever backend the engine is
driving). Specs are frozen and hashable so jitted step builders can be
cached per spec.

Registered workloads (``get(name)`` / ``names()``):

* ``life`` — the existing semantics, bit-exact (uint8, radius-1 box).
* ``heat`` — float32 5-point diffusion (explicit Euler, alpha=0.1).
* ``gray_scott`` — two-channel float32 reaction-diffusion.
* ``wireworld`` — 4-state automaton (empty/head/tail/conductor).
* ``lenia`` — wide-radius (r=8) float32 smooth-growth automaton; its
  Gaussian ring kernel is exactly rank-2 factorizable, the workload the
  separable/FFT engine families (PR 20) exist for.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Callable

import numpy as np

#: Radius-1 all-neighbour box (Moore neighbourhood), center zero.
BOX3 = ((1, 1, 1), (1, 0, 1), (1, 1, 1))
#: Radius-1 5-point cross (von Neumann), center zero.
CROSS3 = ((0, 1, 0), (1, 0, 1), (0, 1, 0))

#: Singular values below ``s_max * _SEP_RANK_CUTOFF`` are factorization
#: noise, not rank — the residual past the kept rank must be exactly
#: this kind of float64 dust for a table to count as factorizable.
_SEP_RANK_CUTOFF = 1e-12


@functools.lru_cache(maxsize=None)
def _separable_factors(weights: tuple, radius: int):
    """Low-rank row x col factorization of a weight table, or None.

    Returns ``((u_0, v_0), ..., (u_{k-1}, v_{k-1}))`` float64 vectors
    with ``w == sum_k outer(u_k, v_k)`` to float64-SVD exactness, where
    ``k`` is the table's numerical rank. A table only factors when
    ``k <= radius`` — past that the row+col pass count ``2*k*(2r+1)``
    stops beating the ``(2r+1)^2 - 1`` offset walk, and the zero-center
    constraint means every table is at least rank 2 (a rank-1 outer
    product with a zero center needs a zero row or column), so no
    radius-1 table ever factors. Cached per (weights, radius): legality
    gates hit this through ``StencilSpec.separable_rank`` without
    re-running the SVD.
    """
    w = np.asarray(weights, np.float64)
    u, s, vt = np.linalg.svd(w)
    if s[0] == 0.0:
        return None
    rank = int((s > s[0] * _SEP_RANK_CUTOFF).sum())
    if rank > radius:
        return None
    return tuple((u[:, k] * s[k], vt[k, :]) for k in range(rank))


@dataclass(frozen=True)
class StencilSpec:
    """One servable stencil workload.

    ``weights`` is a ``(2*radius+1)``-square nested tuple with a ZERO
    center — the engine aggregates ``sum(w * neighbour)`` over nonzero
    entries in row-major order (fixed order: bit-exact for integer
    dtypes, reproducible for floats). ``pre(board, xp)`` optionally maps
    the board to the field being aggregated (wireworld counts electron
    HEADS, not raw state values). ``update(center, agg, xp)`` is the
    pure rule; ``xp`` is ``numpy`` or ``jax.numpy``. Multi-channel
    boards carry channels on the LEADING axis — the engine only ever
    shifts the last two axes, so channels broadcast for free.
    """

    name: str
    radius: int
    dtype: str
    weights: tuple
    update: Callable
    channels: int = 1
    boundary: str = "torus"
    pre: Callable | None = None
    init: Callable | None = None
    states: int | None = None
    #: Independent NumPy oracle; None means "derive from the offset
    #: table" (``engine.step_numpy``). ``life`` pins the historical
    #: oracle so the generic path is gated against the original truth.
    oracle_step: Callable | None = None
    extra: tuple = field(default=())

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(self.dtype)

    @property
    def is_float(self) -> bool:
        return np.issubdtype(self.np_dtype, np.floating)

    @functools.cached_property
    def separable_rank(self) -> int | None:
        """Numerical rank of the weight table when it factors into
        ``rank`` row x col passes (``rank <= radius``), else None.
        Cached on the instance (``cached_property`` writes through
        ``__dict__``, so frozen is fine) — legality gates read this
        per call without re-factorizing."""
        f = _separable_factors(self.weights, self.radius)
        return None if f is None else len(f)

    def board_shape(self, ny: int, nx: int) -> tuple:
        """Full board shape for an ``ny x nx`` grid (channels leading)."""
        return (self.channels, ny, nx) if self.channels > 1 else (ny, nx)

    def valid_board(self, board: np.ndarray) -> bool:
        """Domain check used by the chaos/consistency guards: automata
        states must stay in range, float fields must stay finite."""
        board = np.asarray(board)
        if self.states is not None:
            return bool(np.isin(board, np.arange(self.states)).all())
        if self.is_float:
            return bool(np.isfinite(board).all())
        return True


# --------------------------------------------------------------------------
# Rule bodies (module-level so specs stay hashable + picklable).

def _life_update(center, agg, xp):
    # Exactly ops.life_ops.life_rule: birth on 3, survival on 2.
    return ((agg == 3) | ((agg == 2) & (center == 1))).astype(center.dtype)


HEAT_ALPHA = 0.1


def _heat_update(center, agg, xp):
    # Explicit Euler 5-point diffusion; agg is the cross sum, so
    # (agg - 4c) is the discrete Laplacian.
    return (center + HEAT_ALPHA * (agg - 4 * center)).astype(center.dtype)


GS_DU, GS_DV, GS_F, GS_K, GS_DT = 0.16, 0.08, 0.04, 0.06, 1.0


def _gray_scott_update(center, agg, xp):
    # center/agg: (2, ny, nx) — channel 0 is U, channel 1 is V; agg is
    # the per-channel 5-point cross sum, so agg - 4*center is the
    # Laplacian of each channel.
    u, v = center[0], center[1]
    lu = agg[0] - 4 * u
    lv = agg[1] - 4 * v
    uvv = u * v * v
    un = u + (GS_DU * lu - uvv + GS_F * (1 - u)) * GS_DT
    vn = v + (GS_DV * lv + uvv - (GS_F + GS_K) * v) * GS_DT
    return xp.stack([un, vn]).astype(center.dtype)


#: Lenia growth-bell parameters. Weights are normalized to sum 1, so the
#: aggregate is a weighted mean in [0, 1]; the growth map then peaks at
#: LENIA_MU with width LENIA_SIGMA. SIGMA and DT are chosen so one
#: step's error amplification ``1 + DT * max|g'|`` stays ~1.5 — an
#: 8-step parity window amplifies float noise ~25x, which the family
#: parity tolerances (engine.parity_tol_for) are sized against.
LENIA_MU, LENIA_SIGMA, LENIA_DT = 0.35, 0.25, 0.1


def _lenia_update(center, agg, xp):
    # Smooth growth: Gaussian bell mapped to [-1, 1], explicit Euler,
    # state clipped to the unit interval.
    g = 2.0 * xp.exp(
        -((agg - LENIA_MU) ** 2) / (2.0 * LENIA_SIGMA ** 2)) - 1.0
    return xp.clip(center + LENIA_DT * g, 0.0, 1.0).astype(center.dtype)


def _wireworld_pre(board, xp):
    # Aggregate counts electron HEADS only.
    return (board == 1).astype(board.dtype)


def _wireworld_update(center, agg, xp):
    # 0 empty -> empty, 1 head -> tail(2), 2 tail -> conductor(3),
    # 3 conductor -> head(1) iff 1 or 2 head neighbours, else stays.
    is_head = center == 1
    is_tail = center == 2
    is_cond = center == 3
    excite = (agg == 1) | (agg == 2)
    nxt = is_head * 2 + is_tail * 3 + is_cond * (3 - 2 * excite)
    return nxt.astype(center.dtype)


# --------------------------------------------------------------------------
# Initial-board builders (NumPy, host-side; rng is np.random.Generator).

def _life_init(rng, shape):
    ny, nx = shape
    return (rng.random((ny, nx)) < 0.33).astype(np.uint8)


def _heat_init(rng, shape):
    ny, nx = shape
    return rng.random((ny, nx)).astype(np.float32)


def _gray_scott_init(rng, shape):
    ny, nx = shape
    u = np.ones((ny, nx), np.float32)
    v = np.zeros((ny, nx), np.float32)
    # A few perturbation squares kick off the pattern; the bulk stays
    # at the trivial (U=1, V=0) fixed point.
    for _ in range(max(1, (ny * nx) // 4096)):
        cy = int(rng.integers(0, ny))
        cx = int(rng.integers(0, nx))
        s = 4
        ys = np.arange(cy - s, cy + s) % ny
        xs = np.arange(cx - s, cx + s) % nx
        u[np.ix_(ys, xs)] = 0.5
        v[np.ix_(ys, xs)] = 0.25
    return np.stack([u, v])


def _wireworld_init(rng, shape):
    ny, nx = shape
    # Random mix biased toward empty/conductor with sparse head/tail —
    # enough live signal for parity fuzz without hand-drawing circuits.
    return rng.choice(
        np.arange(4, dtype=np.uint8), size=(ny, nx),
        p=[0.55, 0.05, 0.05, 0.35]).astype(np.uint8)


def _lenia_init(rng, shape):
    ny, nx = shape
    return rng.random((ny, nx)).astype(np.float32)


def make_lenia(radius: int, name: str | None = None) -> StencilSpec:
    """Wide-radius smooth automaton at any radius (bench sweeps use
    ephemeral specs; only radius 8 is registered as ``"lenia"``).

    The kernel is a normalized Gaussian ring ``outer(g, g)`` with the
    center zeroed — an even-rank (exactly rank-2) table at any radius
    >= 2, so the separable family factors it exactly while the offset
    walk pays the full ``(2r+1)^2 - 1`` gathers.
    """
    side = 2 * radius + 1
    g = np.exp(-0.5 * ((np.arange(side) - radius) / (0.35 * radius)) ** 2)
    w = np.outer(g, g)
    w[radius, radius] = 0.0
    w /= w.sum()
    weights = tuple(tuple(float(x) for x in row) for row in w)
    return StencilSpec(
        name=name or f"lenia_r{radius}", radius=radius, dtype="float32",
        weights=weights, update=_lenia_update, init=_lenia_init)


def _life_oracle(board):
    from mpi_and_open_mp_tpu.ops import life_ops

    return life_ops.life_step_numpy(board)


# --------------------------------------------------------------------------
# Registry.

_REGISTRY: dict[str, StencilSpec] = {}


def register(spec: StencilSpec) -> StencilSpec:
    """Validate + register. Integer 0/1 tables and float tables (any
    value, any rank — even-rank factorizable Gaussian rings included)
    are both fine; the only hard constraints are the square shape, the
    zero center, and finiteness. ``separable_rank`` is warmed here so
    every later legality gate is a cached attribute read, never an SVD.
    """
    if spec.name in _REGISTRY:
        raise ValueError(f"stencil {spec.name!r} already registered")
    side = 2 * spec.radius + 1
    w = np.asarray(spec.weights)
    if w.shape != (side, side):
        raise ValueError(
            f"stencil {spec.name!r}: weights shape {w.shape} != "
            f"({side}, {side}) for radius {spec.radius}")
    if w[spec.radius, spec.radius] != 0:
        raise ValueError(
            f"stencil {spec.name!r}: weights center must be 0 (the rule "
            "sees the center via the `center` argument)")
    if not np.isfinite(w.astype(np.float64)).all():
        raise ValueError(
            f"stencil {spec.name!r}: weights must be finite")
    spec.separable_rank
    _REGISTRY[spec.name] = spec
    return spec


def get(name: str) -> StencilSpec:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"unknown stencil workload {name!r}; registered: "
            f"{', '.join(sorted(_REGISTRY))}") from None


def names() -> tuple[str, ...]:
    return tuple(sorted(_REGISTRY))


LIFE = register(StencilSpec(
    name="life", radius=1, dtype="uint8", weights=BOX3,
    update=_life_update, states=2, init=_life_init,
    oracle_step=_life_oracle))

HEAT = register(StencilSpec(
    name="heat", radius=1, dtype="float32", weights=CROSS3,
    update=_heat_update, init=_heat_init))

GRAY_SCOTT = register(StencilSpec(
    name="gray_scott", radius=1, dtype="float32", weights=CROSS3,
    update=_gray_scott_update, channels=2, init=_gray_scott_init))

WIREWORLD = register(StencilSpec(
    name="wireworld", radius=1, dtype="uint8", weights=BOX3,
    update=_wireworld_update, pre=_wireworld_pre, states=4,
    init=_wireworld_init))

LENIA = register(make_lenia(8, "lenia"))
