"""Distributed Game-of-Life simulation engine.

The TPU-native re-design of the reference's four Life drivers:

* ``layout="row"``  ≙ 1-D row-strip decomposition (``3-life/life_mpi.c``)
* ``layout="col"``  ≙ 1-D column strips via strided datatypes (``4-life/life_mpi.c``)
* ``layout="cart"`` ≙ 2-D Cartesian blocks (``6-cartesian/life_cart.c``)
* ``layout="serial"`` ≙ the single-process oracle (``3-life/life2d.c``)

Instead of per-rank slabs with in-place ghost writes, the global board is ONE
``jax.Array`` sharded over a ``Mesh``; the step is either

* ``impl="roll"``: the global circular-shift step — XLA inserts
  collective-permutes for the sharded axes. Works for any board size: a
  board that doesn't divide the mesh is stored padded to the next even
  multiple and un/re-padded inside the jitted step (static shapes), which
  covers the reference's last-rank-absorbs-remainder decomposition
  (``3-life/life_mpi.c:178-183``) without its rank-loop idiom; or
* ``impl="halo"``: an explicit ``shard_map`` step — ``lax.ppermute``
  depth-``k`` halo exchange then ``k`` fused local stencil steps per round
  (amortising one exchange over ``k`` steps; state-identical to stepping
  ``k`` times). Requires the sharded axes to divide the board.
* ``impl="pallas"``: like ``halo`` but the local stencil is a Pallas TPU
  kernel; single-device meshes use the whole-board-in-VMEM multi-step
  kernel (see ``ops.pallas_life``).
* ``impl="bitfused"`` (row/col/cart): the scale-out flagship — each
  shard holds a bit-packed slab (``ops.bitlife``), exchanges an
  up-to-4-word (=128-cell-row) y halo and/or an up-to-128-column x halo
  by ``ppermute`` (unsharded axes wrap locally; cart corners ride the
  sequenced exchange), then runs up to 128 fused steps slab-resident
  through the fused kernel before the next exchange. One collective
  round per up to 128 steps instead of per step; the ICI analogue of
  the reference's ghost Send/Recv (``3-life/life_mpi.c:198-209``,
  ``4-life:197-208``) amortised up to 128-fold. Any board shape on any
  mesh the planner (``bitlife.plan_sharded_bits``) accepts — unaligned
  boards (the 500x500 flagship included) live in a word/lane-aligned
  padded frame whose torus wrap is kept exact via periodic mirrors and
  funnel-shifted wrap halos. A 1-device mesh has no neighbours, so on
  TPU it dispatches straight to the serial whole-board stepper (ghost
  redundancy and exchange rounds buy nothing there); the exchange
  machinery engages from 2 devices.

``impl="auto"``: serial boards pick ``pallas`` on TPU / ``roll``
elsewhere; sharded layouts pick ``bitfused`` on TPU whenever the
planner covers the board/mesh geometry, else ``halo`` when shapes
divide, else ``roll``.

A STACKED ``(B, ny, nx)`` ``initial_board`` puts the sim in batched
mode (serial layout only): all B independent boards advance in ONE
device dispatch through the batched native engines
(``ops.pallas_life.life_run_vmem_batch``; ``impl="roll"`` vmaps the
unpacked step instead), ``collect()`` returns the stack, and the
honesty gate (``debug_check``/guards) checks EVERY board against the
NumPy oracle individually. The serve-layer micro-batcher
(``mpi_and_open_mp_tpu.serve``) is the request-collecting front door
over the same engines.

The run loop preserves the reference's ordering (``3-life/life_mpi.c:51-62``):
at step ``i``, save a snapshot when ``i % save_steps == 0`` (i.e. *before*
stepping), then advance one step. Collect-to-host is ``jax.device_get`` of
the sharded array — the ``MPI_Gather``/manual-recv-loop equivalent
(``5-gather/life_mpi.c:178``, ``3-life/life_mpi.c:185-196``).

Since the stencil subsystem (``mpi_and_open_mp_tpu.stencils``) landed,
the sim is workload-generic: ``workload="life"`` (the default) is the
historical behaviour bit-for-bit, while any other registered
:class:`~mpi_and_open_mp_tpu.stencils.StencilSpec` (heat, gray_scott,
wireworld, ...) runs through the SAME roll / halo / generic-Pallas
machinery — spec dtype, spec oracle, spec domain check, channel axes
riding in front of the sharded board axes. The bit-packed engines
(``bitfused`` and the batched native dispatch) encode Life's 0/1 state
specifically, so they stay ``life``-only; ``impl="auto"`` for other
workloads picks ``halo`` when the board divides the mesh, else ``roll``.
"""

from __future__ import annotations

import functools
import os
import time
from typing import Callable

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mpi_and_open_mp_tpu.ops import life_ops
from mpi_and_open_mp_tpu.parallel import halo, haloplan, mesh as mesh_lib
from mpi_and_open_mp_tpu.utils import vtk as vtk_lib
from mpi_and_open_mp_tpu.utils.config import LifeConfig

LAYOUTS = ("serial", "row", "col", "cart")
IMPLS = ("auto", "roll", "halo", "pallas", "bitfused")

# The bitfused 1-device serial dispatch is TPU-only by default (on CPU
# the interpret-mode suite keeps exercising the exchange machinery the
# fast path bypasses); tests flip this to cover the dispatch itself.
_BITFUSED_1DEV_SERIAL_ON_CPU = False


def _layout_spec(layout: str, channels: int = 1) -> P:
    axes = {
        "serial": (),
        "row": ("y", None),
        "col": (None, "x"),
        "cart": ("y", "x"),
    }[layout]
    # Multi-channel stencils carry the channel axis in FRONT of the board
    # axes; it is never sharded (every device owns all fields of its
    # cells, the layout that keeps the update local).
    if channels > 1 and axes:
        axes = (None, *axes)
    return P(*axes)


def _default_mesh(layout: str) -> Mesh | None:
    if layout == "serial":
        return None
    if layout == "row":
        return mesh_lib.make_mesh_1d(axis="y")
    if layout == "col":
        return mesh_lib.make_mesh_1d(axis="x")
    return mesh_lib.make_mesh_2d()


def _mesh_divisors(layout: str, mesh: Mesh | None) -> tuple[int, int]:
    """(py, px) the board axes must divide for even sharding under ``layout``."""
    if layout == "serial" or mesh is None:
        return (1, 1)
    py = mesh.shape.get("y", 1) if layout in ("row", "cart") else 1
    px = mesh.shape.get("x", 1) if layout in ("col", "cart") else 1
    return (py, px)


def _divisible(shape: tuple[int, int], layout: str, mesh: Mesh | None) -> bool:
    ny, nx = shape
    py, px = _mesh_divisors(layout, mesh)
    return ny % py == 0 and nx % px == 0


def _ceil_to(n: int, m: int) -> int:
    return -(-n // m) * m


def _oracle_step(board: np.ndarray, spec) -> np.ndarray:
    """One NumPy-oracle step of ``spec``; for single-channel specs a
    (B, ny, nx) stack steps per board (a multi-channel 3D array IS one
    board — channels lead, there is no batched multi-channel mode)."""
    from mpi_and_open_mp_tpu.stencils import step_numpy

    if spec.channels == 1 and board.ndim == 3:
        return np.stack([step_numpy(spec, b) for b in board])
    return step_numpy(spec, board)


def _note_retrace(fn: str) -> None:
    """Retrace accounting (``obs.metrics``): called from INSIDE jitted
    ``advance`` bodies, which only execute on a jit-cache miss — so the
    counter reads "how many distinct programs XLA built for this
    function", the number that explains a slow first segment or a
    shape-churn pathology. Free at execution time by construction."""
    from mpi_and_open_mp_tpu.obs import metrics

    metrics.inc("jit.retrace", fn=fn)


class LifeSim:
    """One Life run: sharded board state + compiled steppers + snapshot IO."""

    def _bitfused_plan(self, layout: str, shape: tuple[int, int]):
        """The packed-path plan for this board/mesh, or None (serial
        layouts, or geometry the frame-padding scheme can't cover)."""
        from mpi_and_open_mp_tpu.ops import bitlife

        if layout == "serial":
            return None
        py, px = _mesh_divisors(layout, self.mesh)
        return bitlife.plan_sharded_bits(
            shape, py, px,
            y_sharded=layout in ("row", "cart"),
            x_sharded=layout in ("col", "cart"),
        )

    def __init__(
        self,
        cfg: LifeConfig,
        layout: str = "row",
        impl: str = "auto",
        mesh: Mesh | None = None,
        fuse_steps: int = 1,
        dtype=None,
        outdir: str | os.PathLike | None = None,
        checkpoint_dir: str | os.PathLike | None = None,
        checkpoint_every: int = 0,
        initial_board: np.ndarray | None = None,
        initial_step: int = 0,
        workload: str = "life",
    ):
        from mpi_and_open_mp_tpu import stencils

        if layout not in LAYOUTS:
            raise ValueError(f"layout must be one of {LAYOUTS}, got {layout!r}")
        if impl not in IMPLS:
            raise ValueError(f"impl must be one of {IMPLS}, got {impl!r}")
        self.workload = str(workload)
        self.spec = stencils.get(self.workload)
        if dtype is None:
            # Historical default for life (uint8) IS the spec dtype, so
            # the pre-workload constructor signature is unchanged in
            # behaviour; other specs bring their own cell dtype.
            dtype = jnp.dtype(self.spec.dtype)
        self._np_dtype = self.spec.np_dtype
        if self.workload != "life":
            # The bit-packed engines encode Life's 0/1 state; everything
            # else runs the generic roll / halo / generic-Pallas paths.
            if impl == "bitfused":
                raise ValueError(
                    f"impl='bitfused' is a bit-packed Life engine; "
                    f"workload={self.workload!r} runs 'roll', 'halo' or "
                    "'pallas' (sharded)")
            if impl == "pallas" and layout == "serial":
                raise ValueError(
                    "serial impl='pallas' dispatches the bit-packed Life "
                    f"VMEM engine; workload={self.workload!r} uses "
                    "impl='roll' (serial) or 'pallas' on a sharded layout")
        # Batched mode: a STACKED (B, ny, nx) initial board advances all B
        # independent boards per dispatch through the batched native
        # engines (ops.pallas_life.life_run_vmem_batch) — the model-layer
        # face of the serve-layer micro-batching. Serial layout only (a
        # batch of sharded boards is the serve layer's bucketing problem,
        # not one mesh program), and no VTK/checkpoint channels (both
        # serialise ONE board; batched runs are throughput runs).
        self.batch: int | None = None
        if (initial_board is not None
                and np.asarray(initial_board).ndim
                == 3 + (self.spec.channels > 1)):
            if self.spec.channels > 1 or self.workload != "life":
                # A 3D multi-channel array is ONE board (channels lead);
                # stacks of non-life boards are the serve layer's
                # bucketing problem, not a model-layer mode — the batched
                # native engines are bit-packed Life.
                raise ValueError(
                    f"workload={self.workload!r} has no batched mode; "
                    "submit stacks through the serve batcher instead")
            if layout != "serial":
                raise ValueError(
                    "stacked (B, ny, nx) boards need layout='serial'; "
                    "sharded layouts advance one board per mesh program"
                )
            if impl in ("halo", "bitfused"):
                raise ValueError(
                    f"impl={impl!r} has no batched form; use 'auto', "
                    "'pallas' (batched native dispatch) or 'roll'"
                )
            if outdir is not None or checkpoint_dir is not None:
                raise ValueError(
                    "batched runs have no snapshot/checkpoint channels "
                    "(both serialise one board); drop outdir/checkpoint_dir"
                )
            self.batch = int(np.asarray(initial_board).shape[0])
        self.cfg = cfg
        self.layout = layout
        self.mesh = mesh if mesh is not None else _default_mesh(layout)
        self.fuse_steps = max(1, int(fuse_steps))
        self.dtype = dtype
        self.outdir = os.fspath(outdir) if outdir is not None else None
        self.checkpoint_dir = (
            os.fspath(checkpoint_dir) if checkpoint_dir is not None else None
        )
        # Periodic restart cadence (steps between Orbax checkpoints inside
        # run(); 0 = only the save_steps cadence writes checkpoints) and
        # the per-run recovery provenance the guards append to.
        self.checkpoint_every = max(0, int(checkpoint_every))
        self.recoveries: list[str] = []
        self._probe = None  # lazy (board, oracle) pair for _probe_case
        self.step_count = int(initial_step)

        divisible = _divisible(cfg.shape, layout, self.mesh)
        plan = (
            self._bitfused_plan(layout, cfg.shape)
            if impl in ("auto", "bitfused") and self.workload == "life"
            else None
        )
        if impl == "auto" and self.workload != "life":
            # Generic-spec auto: the explicit-halo shard_map path when
            # the board divides the mesh, else the global roll step.
            impl = "halo" if (layout != "serial" and divisible) else "roll"
        elif impl == "auto":
            on_tpu = jax.default_backend() == "tpu"
            if self.batch is not None:
                # The batched dispatcher compiles on EVERY backend (off-TPU
                # it routes to the vmapped packed-XLA loop, never interpret
                # mode — ops.pallas_life.native_path_batch), so batched
                # auto is always the native dispatch.
                impl = "pallas"
            elif layout == "serial":
                # Pallas only where it compiles natively; elsewhere it would
                # run in interpret mode, orders of magnitude slower.
                impl = "pallas" if on_tpu else "roll"
            elif on_tpu and plan is not None:
                # Best sharded path whenever the frame-padding plan covers
                # the geometry (any board shape, aligned or not): one
                # collective round per <=128 fused steps. TPU-only — on
                # CPU the kernel would run in interpret mode.
                impl = "bitfused"
            elif divisible:
                impl = "halo"
            else:
                impl = "roll"
        if impl == "halo" and layout == "serial":
            raise ValueError(
                "impl='halo' needs a sharded layout (row/col/cart); "
                "serial runs use impl='roll' or 'pallas'"
            )
        if impl in ("halo", "pallas") and not divisible and layout != "serial":
            raise ValueError(
                f"impl={impl!r} needs board {cfg.shape} divisible by mesh "
                f"{dict(self.mesh.shape)}; use impl='roll' (uneven shards OK)"
            )
        if impl == "bitfused":
            if layout == "serial":
                raise ValueError(
                    "impl='bitfused' needs a sharded layout (row/col/cart); "
                    "serial big boards already take the fused kernel via "
                    "impl='pallas'"
                )
            if plan is None:
                raise ValueError(
                    f"impl='bitfused' can't plan board {cfg.shape} over "
                    f"mesh {dict(self.mesh.shape)}: a shard is too small "
                    "to carry a fused halo next to its frame padding; use "
                    "impl='halo' or 'roll'"
                )
        self.impl = impl
        self._plan = plan if impl == "bitfused" else None

        if impl in ("halo", "pallas") and layout != "serial":
            py, px = _mesh_divisors(layout, self.mesh)
            local = min(cfg.ny // py, cfg.nx // px)
            if self.fuse_steps * self.spec.radius > local:
                raise ValueError(
                    f"fuse_steps={self.fuse_steps} x radius "
                    f"{self.spec.radius} exceeds the smallest local shard "
                    f"extent ({local}); a halo cannot be deeper than the "
                    f"shard it pads"
                )

        self.sharding = (
            NamedSharding(self.mesh, _layout_spec(layout, self.spec.channels))
            if self.mesh is not None
            else None
        )
        # Uneven boards: store padded to the next mesh-even multiple; the
        # roll step un/re-pads inside jit so the torus wrap stays on the
        # LOGICAL (ny, nx) coordinates, never the padded ones. The packed
        # path pads further, to its word/lane-aligned frame, and keeps the
        # torus via periodic mirrors (ops.bitlife module docs).
        if self._plan is not None:
            self.padded_shape = self._plan.frame
        else:
            py, px = _mesh_divisors(layout, self.mesh)
            self.padded_shape = (_ceil_to(cfg.ny, py), _ceil_to(cfg.nx, px))
        if initial_board is not None:
            board = np.asarray(initial_board, dtype=self._np_dtype)
            expect = (
                (self.batch, *cfg.shape) if self.batch is not None
                else self.spec.board_shape(*cfg.shape)
            )
            if board.shape != expect:
                raise ValueError(
                    f"initial_board {board.shape} != expected {expect}"
                )
        elif self.workload == "life":
            board = cfg.board()
        else:
            # Non-life boards come from the spec's own initialiser (the
            # LifeConfig cell list encodes Life patterns specifically).
            board = self.spec.init(np.random.default_rng(0xD1CE), cfg.shape)
        if self.batch is None and self.padded_shape != cfg.shape:
            full = np.zeros(
                self.spec.board_shape(*self.padded_shape), dtype=board.dtype)
            full[..., : cfg.ny, : cfg.nx] = board
            board = full
        self._initial = board
        self._initial_step = int(initial_step)
        board = jnp.asarray(board, dtype=dtype)
        self.board = (
            jax.device_put(board, self.sharding) if self.sharding else board
        )
        self._advance = self._build_advance()

    # ---------------------------------------------------------- step builders

    def _halo_plan(self, k: int) -> "haloplan.HaloPlan":
        """The persistent exchange plan for one ``k``-step fused round
        (derived once per geometry, ``lru_cache``d in ``haloplan``)."""
        py, px = _mesh_divisors(self.layout, self.mesh)
        return haloplan.plan_halo(
            self.layout, (py, px),
            (self.padded_shape[0] // py, self.padded_shape[1] // px),
            self.spec.radius, k, channels=self.spec.channels,
        )

    def _local_fused_step(self, block: jnp.ndarray, k: int) -> jnp.ndarray:
        """One fused round of ``k`` local steps (each consuming
        ``radius`` halo cells per side), scheduled by the persistent
        halo plan: ghost ``ppermute``s overlap the interior stencil when
        the geometry allows (``parallel.haloplan``), else the historic
        blocking ``halo_pad_*`` concat."""
        return haloplan.fused_step(self._halo_plan(k), self._padded_step,
                                   block)

    def _padded_step(self, padded: jnp.ndarray) -> jnp.ndarray:
        if self.impl == "pallas":
            from mpi_and_open_mp_tpu.ops import pallas_life

            if self.workload == "life":
                return pallas_life.life_step_padded_pallas(padded)
            return pallas_life.stencil_step_padded_pallas(self.spec, padded)
        from mpi_and_open_mp_tpu.stencils import step_padded

        return step_padded(self.spec, padded, jnp)

    def _build_advance(self) -> Callable[[jnp.ndarray, int], jnp.ndarray]:
        """Return ``advance(board, n)`` running ``n`` steps, jit-cached on ``n``."""
        if self.batch is not None:
            return self._build_batched_advance()

        if self.impl == "bitfused":
            return self._build_bitfused_advance()

        if self.impl == "pallas" and (
            self.mesh is None or self.mesh.size == 1
        ):
            from mpi_and_open_mp_tpu.ops import pallas_life

            def advance(board, n):
                return pallas_life.life_run_vmem(board, n)

            return advance

        if self.impl == "roll" or self.layout == "serial":
            from mpi_and_open_mp_tpu.stencils import step_roll

            sharding = self.sharding
            spec_ = self.spec
            ny, nx = self.cfg.shape
            pad_y = self.padded_shape[0] - ny
            pad_x = self.padded_shape[1] - nx
            lead = ((0, 0),) if spec_.channels > 1 else ()

            @functools.partial(jax.jit, static_argnums=1)
            def advance(board, n):
                _note_retrace("life_advance_roll")

                def body(_, b):
                    if pad_y or pad_x:
                        v = step_roll(spec_, b[..., :ny, :nx], jnp)
                        b = jnp.pad(v, (*lead, (0, pad_y), (0, pad_x)))
                    else:
                        b = step_roll(spec_, b, jnp)
                    if sharding is not None:
                        b = lax.with_sharding_constraint(b, sharding)
                    return b

                return lax.fori_loop(0, n, body, board)

            return advance

        # shard_map halo/pallas path, with k-step fusion per exchange round.
        spec = _layout_spec(self.layout, self.spec.channels)
        k = self.fuse_steps
        # Provenance: the persistent plan's schedule stamp for the main
        # round depth ("overlap:*" when the ghost exchange hides behind
        # the interior stencil, "seq:halo" with the reason otherwise).
        self.plan_note = self._halo_plan(k).engine

        def make_smapped(kk: int):
            # check_vma=False: the Pallas per-shard kernel can't annotate
            # varying-mesh-axes on its out_shape; the specs are authoritative.
            return mesh_lib.shard_map(
                lambda b: self._local_fused_step(b, kk),
                mesh=self.mesh,
                in_specs=spec,
                out_specs=spec,
                check_vma=False,
            )

        smapped_k = make_smapped(k)
        smapped_cache = {k: smapped_k}

        @functools.partial(jax.jit, static_argnums=1)
        def advance(board, n):
            _note_retrace("life_advance_halo")
            rounds, rem = divmod(n, k)
            board = lax.fori_loop(0, rounds, lambda _, b: smapped_k(b), board)
            if rem:
                if rem not in smapped_cache:
                    smapped_cache[rem] = make_smapped(rem)
                board = smapped_cache[rem](board)
            return board

        return advance

    def _build_batched_advance(self) -> Callable:
        """Stacked-board steppers: all B boards advance in ONE dispatch.

        ``impl="pallas"`` is the batched native dispatch
        (``ops.pallas_life.life_run_vmem_batch`` — runtime-scalar step
        count, one compiled program per stack shape on every backend);
        ``impl="roll"`` is the unpacked roll step vmapped over the stack
        (jit-cached per static ``n``, like the single-board roll).
        """
        if self.impl == "pallas":
            from mpi_and_open_mp_tpu.ops import pallas_life

            self.plan_note = "batch:" + pallas_life.native_path_batch(
                (self.batch, *self.cfg.shape),
                on_tpu=jax.default_backend() == "tpu",
            )

            def advance(board, n):
                return pallas_life.life_run_vmem_batch(board, n)

            return advance

        @functools.partial(jax.jit, static_argnums=1)
        def advance(board, n):
            _note_retrace("life_advance_roll_batch")
            step = jax.vmap(life_ops.life_step_roll)
            return lax.fori_loop(0, n, lambda _, b: step(b), board)

        return advance

    def _build_bitfused_advance(self) -> Callable:
        """Packed scale-out path: ppermute packed halos, fuse <=128 steps.

        Each shard packs its slab once per ``advance`` call (pack/unpack are
        fused XLA ops, amortised over the whole step budget), then loops:
        exchange the plan's halo word rows (row layout; plus halo columns
        first on col/cart meshes — corners ride the y-exchange of the
        x-extended slab, the reference's 2-phase trick at
        ``6-cartesian/life_cart.c:275-279``), run ``min(rem, k_max)``
        steps slab-resident via the fused kernel, repeat. Unaligned
        boards live in the plan's padded frame: the halo calls slide the
        torus wrap onto the logical shape and refresh the periodic
        mirrors (``halo.packed_halo_*``/``bitlife.wrap_y_padded``), so
        the same one-collective-per-k_max-steps economy covers every
        shape — the reference's per-step ghost Send/Recv
        (``3-life/life_mpi.c:198-209``) amortised up to 128-fold. ``n``
        is a runtime scalar — one compiled program serves every segment
        length.
        """
        from mpi_and_open_mp_tpu.ops import bitlife

        plan = self._plan
        mesh = self.mesh
        spec = _layout_spec(self.layout)
        interpret = jax.default_backend() != "tpu"
        dtype = self.dtype

        if mesh.size == 1 and (not interpret
                               or _BITFUSED_1DEV_SERIAL_ON_CPU):
            # A 1-device mesh has no neighbours: the ghost-window
            # redundancy ((nw_s+2h)/nw_s ≈ 1.5x extra cells at the 500²
            # flagship) and the per-round exchange+launch cost buy
            # nothing, so dispatch the board to the serial whole-board
            # stepper — the sharded machinery begins at 2 devices. The
            # plan's frame padding is sliced off/restored around the
            # call (once per advance, amortised over the whole step
            # budget); the serial dispatcher does its own padding.
            # TPU-only: on CPU the interpret-mode tests keep exercising
            # the exchange machinery this fast path would bypass.
            from mpi_and_open_mp_tpu.ops.pallas_life import (
                life_run_vmem, native_path)

            ny, nx = self.cfg.shape
            fy, fx = plan.frame
            # on_tpu must mirror life_run_vmem's own dispatch decision
            # or this provenance label could name a path that never runs.
            self.plan_note = ("serial-1dev:"
                              f"{native_path((ny, nx), on_tpu=not interpret)}")

            @jax.jit
            def advance(board, n):
                _note_retrace("life_advance_bitfused")
                out = life_run_vmem(board[:ny, :nx], jnp.int32(n))
                out = jnp.pad(out, ((0, fy - ny), (0, fx - nx)))
                return lax.with_sharding_constraint(
                    out.astype(dtype), self.sharding)

            return advance

        # Packed overlap: window-mode exact-frame row shards split each
        # round into interior (the raw slab is its own window — the outer
        # h words play the halo role) and two 3h-word edge extensions,
        # so the ghost ppermute flies while the interior kernel runs —
        # one halo word carries 32 board rows, the overlap win
        # multiplied (parallel.haloplan module docs). The haloplan
        # carries the env kill switch + degenerate-geometry gates; depth
        # is the full 32h-bit-row fuse budget of one exchange round.
        eligible = bitlife.plan_overlap_supported(plan)
        hp = (
            haloplan.plan_halo(
                "row", (plan.py, plan.px), (32 * plan.nw_s, plan.W),
                32 * plan.h, 1, pack_layout="packed")
            if eligible else None
        )
        use_overlap = hp is not None and hp.overlap
        # 1-shard / ineligible geometry keeps the bare mode string (the
        # historical note); capable geometry appends the schedule stamp.
        self.plan_note = (
            f"{plan.mode}+{hp.engine}" if hp is not None else plan.mode
        )
        step_call = bitlife.make_plan_stepper(plan, interpret=interpret)
        if use_overlap:
            interior_call, edge_call = bitlife.make_overlap_steppers(
                plan, interpret=interpret)

        def shard_fn(block, n):
            packed = bitlife.pack_board_exact(block)

            def body(carry):
                q, rem = carry
                k = jnp.minimum(rem, plan.k_max)
                kk = k.reshape(1)
                if use_overlap:
                    # Ghosts issued first, consumed last: the interior
                    # window reads only local words, so XLA's scheduler
                    # pairs the permute-start with a done after it.
                    haloplan._note_schedule(hp)
                    top, bot = haloplan.packed_ghosts_y(q, plan.h)
                    mid = interior_call(kk, q)
                    lead = edge_call(
                        kk, jnp.concatenate([top, q[: 2 * plan.h]]))
                    tail = edge_call(
                        kk, jnp.concatenate([q[-2 * plan.h:], bot]))
                    out = jnp.concatenate([lead, mid, tail])
                    return out, rem - k
                # The packed, k_max-amortised ghost exchange: the same
                # ring halos as every other impl, in word rows / lane
                # columns (cf. 3-life/life_mpi.c:203-207, 4-life:197-208).
                # Axes the mesh doesn't shard wrap locally — same content,
                # no collective; unsharded unaligned x needs nothing at
                # all (the kernel's wrap-patched rolls are exact).
                e = q
                if plan.x_sharded:
                    e = halo.packed_halo_x(e, "x", plan.hx, pad=plan.pad_x)
                if plan.y_sharded:
                    e = halo.packed_halo_y(e, "y", plan.h, pad=plan.pad_y)
                else:
                    e = bitlife.local_wrap_y(plan, e)
                return step_call(k.reshape(1), e), rem - k

            q, _ = lax.while_loop(
                lambda c: c[1] > 0, body, (packed, jnp.int32(n))
            )
            return bitlife.unpack_board_exact(q).astype(dtype)

        smapped = mesh_lib.shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(spec, P()),
            out_specs=spec,
            check_vma=False,
        )

        @jax.jit
        def advance(board, n):
            _note_retrace("life_advance_bitfused")
            return smapped(board, jnp.int32(n))

        return advance

    # ------------------------------------------------------------ public API

    def step(self, n: int = 1) -> None:
        """Advance ``n`` steps."""
        self.board = self._advance(self.board, int(n))
        self.step_count += n

    def sync(self) -> None:
        """Wait for all dispatched device work on the board to complete.

        The timing analog of the reference's implicit synchronisation at
        its ``MPI_Wtime`` bracket (``3-life/life_mpi.c:64-67``): JAX
        dispatch is async, so timed sections must end here (or at a host
        fetch). For mesh-placed boards ``block_until_ready`` alone has been
        observed returning early on tunneled-TPU stacks (step-count-
        independent timings — the tell), so a one-element fetch anchors the
        wait to actual completion there; single-device boards skip the
        fetch — blocking works for them and the fetch would cost a full
        host round trip inside the timing bracket.
        """
        from mpi_and_open_mp_tpu.utils.timing import anchor_sync

        anchor_sync(self.board)

    def reset(self) -> None:
        """Restore the initial board without rebuilding compiled steppers."""
        board = jnp.asarray(self._initial, dtype=self.dtype)
        self.board = (
            jax.device_put(board, self.sharding) if self.sharding else board
        )
        self.step_count = self._initial_step

    def save_checkpoint(self, path: str | os.PathLike) -> None:
        """Orbax checkpoint of the live sharded state (see utils.checkpoint:
        no gather-to-root on multi-host, unlike the VTK snapshot path)."""
        from mpi_and_open_mp_tpu.utils import checkpoint

        checkpoint.save(path, self.board, self.step_count)

    @classmethod
    def from_checkpoint(
        cls, path: str | os.PathLike, cfg: LifeConfig, **kwargs
    ) -> "LifeSim":
        """Resume from an Orbax checkpoint, re-sharding onto this mesh."""
        from mpi_and_open_mp_tpu.utils import checkpoint

        board, step = checkpoint.restore(path)
        # Stored state is the padded board; crop to the logical shape (the
        # constructor re-pads for its own mesh).
        board = board[: cfg.ny, : cfg.nx]
        return cls(cfg, initial_board=board, initial_step=step, **kwargs)

    @classmethod
    def from_snapshot(
        cls, cfg: LifeConfig, snapshot_path: str, step: int, **kwargs
    ) -> "LifeSim":
        """Resume a run from a VTK snapshot (checkpoint/restart).

        The reference's periodic VTK dump (``3-life/life_mpi.c:51-58``) is a
        full-board serialisation; this turns it into an actual restart
        capability the reference lacks (SURVEY §5): ``run()`` continues from
        ``step`` with the original save cadence and step budget.
        """
        from mpi_and_open_mp_tpu.utils import vtk as vtk_lib

        board = vtk_lib.read_vtk(snapshot_path)
        return cls(cfg, initial_board=board, initial_step=step, **kwargs)

    def _next_stop(self, i: int, save: bool) -> int:
        """First step index after ``i`` where run() must pause the advance:
        the end of the budget, a snapshot/checkpoint boundary, or a pending
        simulated-preemption point (segments never straddle the preempt
        step — the flush must happen exactly there)."""
        from mpi_and_open_mp_tpu.robust import chaos

        cfg = self.cfg
        stops = [cfg.steps]
        if save and cfg.save_steps > 0:
            stops.append((i // cfg.save_steps + 1) * cfg.save_steps)
        ck = self.checkpoint_every
        if self.checkpoint_dir is not None and ck > 0:
            stops.append((i // ck + 1) * ck)
        plan = chaos.active_plan()
        if plan is not None and plan.preempt_pending(i):
            stops.append(plan.preempt_step)
        return min(s for s in stops if s > i)

    def _segment_lengths(self, save: bool = True) -> list[int]:
        """Distinct ``advance`` step counts a full ``run()`` will request."""
        i = self.step_count
        lengths = set()
        while i < self.cfg.steps:
            next_stop = self._next_stop(i, save)
            lengths.add(next_stop - i)
            i = next_stop
        return sorted(lengths)

    def _consistency_violation(self) -> str | None:
        """The semantic halo-consistency probe, as a description or None.

        Life's stencil output is ALWAYS binary, so a value invariant alone
        can never catch a corrupted halo row after a step — the meaningful
        check is (a) the cheap binary-domain scan plus (b) a single-step
        parity probe: one step of the configured pipeline from the current
        collected board must equal one oracle (NumPy) step. Under an active
        fault plan the n=1 probe program traces through the same injection
        hooks as the segment program (faults are sticky at trace time), so
        a poisoned exchange cannot hide from the probe.
        """
        from mpi_and_open_mp_tpu.stencils import parity_ok

        before = self.collect()
        if not self.spec.valid_board(before):
            # Life/wireworld: out-of-range automaton state; float
            # stencils: non-finite cells. Either way the value invariant
            # broke before the step-parity probe even ran.
            return ("out-of-domain cells on the board"
                    if self.workload != "life"
                    else "non-binary cells on the board")
        after_impl = np.asarray(
            jax.device_get(self._advance(self.board, 1)),
            dtype=self._np_dtype,
        )[..., : self.cfg.ny, : self.cfg.nx]
        expect = _oracle_step(before, self.spec)
        if not parity_ok(self.spec, after_impl, expect):
            if self.batch is not None:
                # PER-BOARD honesty: name every diverging board of the
                # stack, not just "the batch diverged".
                bad = [
                    f"board {b}: {int((after_impl[b] != expect[b]).sum())}"
                    for b in range(after_impl.shape[0])
                    if not np.array_equal(after_impl[b], expect[b])
                ]
                return (
                    f"cells diverge from the oracle after one "
                    f"{self.impl}/{self.layout} step ({'; '.join(bad)})"
                )
            diff = int((after_impl != expect).sum())
            return (
                f"{diff} cells diverge from the oracle after one "
                f"{self.impl}/{self.layout} step"
            )
        # The live-board probe alone can be blind: a corrupted exchange
        # whose effect on THIS board's next step happens to be nil leaves
        # earlier accumulated divergence undetected. The same n=1 program
        # on a fixed dense random board is board-state-independent — a
        # poisoned ghost row over a random edge perturbs neighbour counts
        # with near-certainty.
        probe, probe_expect = self._probe_case()
        after_probe = np.asarray(
            jax.device_get(self._advance(probe, 1)), dtype=self._np_dtype
        )[..., : self.cfg.ny, : self.cfg.nx]
        if not parity_ok(self.spec, after_probe, probe_expect):
            diff = int((after_probe != probe_expect).sum())
            return (
                f"{diff} cells diverge from the oracle after one "
                f"{self.impl}/{self.layout} step on the fixed probe board"
            )
        return None

    def _probe_case(self):
        """Cached ``(device_board, oracle_next)`` for the fixed-probe leg of
        ``_consistency_violation`` — placed exactly like the live board."""
        if self._probe is None:
            rng = np.random.default_rng(0xC0FFEE)
            if self.workload == "life":
                shape = (self.cfg.ny, self.cfg.nx)
                if self.batch is not None:
                    # B DISTINCT dense boards (one rng stream): a fault
                    # that corrupts only some stack positions must still
                    # perturb the board that sits there.
                    shape = (self.batch, *shape)
                host = rng.integers(0, 2, shape, dtype=np.uint8)
            else:
                # The spec's own initialiser is the dense-enough probe
                # state for non-life rules (batched mode is life-only).
                host = np.asarray(
                    self.spec.init(rng, self.cfg.shape),
                    dtype=self._np_dtype)
            if self.batch is None and self.padded_shape != self.cfg.shape:
                full = np.zeros(
                    self.spec.board_shape(*self.padded_shape),
                    dtype=self._np_dtype)
                full[..., : self.cfg.ny, : self.cfg.nx] = host
            else:
                full = host
            b = jnp.asarray(full, dtype=self.dtype)
            b = jax.device_put(b, self.sharding) if self.sharding else b
            self._probe = (b, _oracle_step(host, self.spec))
        return self._probe

    def debug_check(self) -> None:
        """Debug mode: assert halo-exchange consistency on the live state.

        The reference's blocking-send halo pattern is its main unchecked
        race/deadlock surface (SURVEY §5, ``3-life/life_mpi.c:203-207``);
        deterministic collectives make a data race impossible here, so the
        meaningful assertion is semantic: one step through the configured
        (halo/pallas/roll) pipeline must equal the oracle step on the
        gathered global board. Raises AssertionError with a cell-diff count
        on mismatch.
        """
        why = self._consistency_violation()
        if why is not None:
            raise AssertionError(f"halo debug check failed: {why}")

    def _set_board(self, board: np.ndarray, step: int) -> None:
        """Install a host board as the live state (pad + device_put), the
        same placement the constructor performs."""
        board = np.asarray(board, dtype=self._np_dtype)
        if (self.batch is None
                and board.shape[-2:] != tuple(self.padded_shape)):
            full = np.zeros(
                self.spec.board_shape(*self.padded_shape),
                dtype=self._np_dtype)
            full[..., : self.cfg.ny, : self.cfg.nx] = board
            board = full
        b = jnp.asarray(board, dtype=self.dtype)
        self.board = jax.device_put(b, self.sharding) if self.sharding else b
        self.step_count = int(step)

    def _checkpoint_now(self) -> str:
        path = os.path.join(
            self.checkpoint_dir, f"step_{self.step_count:06d}")
        self.save_checkpoint(path)
        return path

    def _guarded_step(self, n: int) -> None:
        """``step(n)`` with the halo-exchange checksum guard armed.

        On a consistency violation: rebuild the compiled steppers with
        injection suppressed (the poisoned traces are cached on the old
        wrappers — a transient fault must not re-fire on the dispatch that
        retries it), restore the pre-segment board and re-step; if even the
        clean re-trace diverges, replay the segment on the NumPy oracle as
        the last resort. Every recovery stamps ``self.recoveries`` and the
        process-wide log ``bench.py`` publishes.
        """
        from mpi_and_open_mp_tpu.robust import chaos, guards

        prev_board = self.board
        prev_step = self.step_count
        self.step(n)
        why = self._consistency_violation()
        if why is None:
            return
        with chaos.suppressed():
            self._advance = self._build_advance()
            self.board = prev_board
            self.step_count = prev_step
            self.step(n)
            still = self._consistency_violation()
        if still is None:
            stamp = f"life_step:{self.impl}:recovered"
            self.recoveries.append(f"{stamp} ({why})")
            guards.record_recovery(stamp)
            return
        board = np.asarray(jax.device_get(prev_board), dtype=self._np_dtype)[
            ..., : self.cfg.ny, : self.cfg.nx]
        for _ in range(n):
            board = _oracle_step(board, self.spec)
        self._set_board(board, prev_step + n)
        stamp = "life_step:numpy-oracle:recovered"
        self.recoveries.append(f"{stamp} ({why}; then {still})")
        guards.record_recovery(stamp)

    def warmup(self) -> None:
        """Compile every stepper a subsequent ``run()`` will hit.

        ``advance`` is jit-cached per static step count ON THIS INSTANCE, so
        warm-up must use the same instance and the same counts; it runs each
        compiled program once on the current board and discards the result
        (``advance`` is functional — state is untouched). Synchronisation
        goes through ``anchor_sync`` (not a whole-array fetch): on
        multi-host runs the board spans non-addressable devices, where a
        full ``device_get`` is impossible.
        """
        from mpi_and_open_mp_tpu.utils.timing import anchor_sync

        for n in self._segment_lengths():
            anchor_sync(self._advance(self.board, n), fetch_all=True)

    def collect(self) -> np.ndarray:
        """Gather the global board to the host (uint8 ``(ny, nx)``).

        On multi-host (``jax.distributed``) runs the board is not fully
        addressable from one process, so the gather goes through a
        cross-process allgather — every host gets the full board, the
        multi-host generalisation of the reference's gather-to-root
        (``5-gather/life_mpi.c:178``).
        """
        if self.board.is_fully_addressable:
            full = np.asarray(
                jax.device_get(self.board), dtype=self._np_dtype)
        else:
            from jax.experimental import multihost_utils

            full = np.asarray(
                multihost_utils.process_allgather(self.board, tiled=True),
                dtype=self._np_dtype,
            )
        # Ellipsis crop: batched boards are (B, ny, nx), the crop applies
        # to the trailing board axes either way.
        return full[..., : self.cfg.ny, : self.cfg.nx]

    def save_snapshot(self) -> str:
        assert self.outdir is not None, "LifeSim(outdir=...) required to save"
        path = vtk_lib.vtk_path(self.outdir, self.step_count)
        # collect() is COLLECTIVE on multi-host runs (cross-process
        # allgather) — every process must enter it; only process 0 writes
        # the file, the reference's write-from-one-rank discipline
        # (3-life/life_mpi.c:54-57; shared-FS double-writes otherwise).
        board = self.collect()
        if jax.process_index() == 0:
            os.makedirs(self.outdir, exist_ok=True)
            vtk_lib.write_vtk(path, board)
        return path

    def save_state(self) -> None:
        """Persist the current step through every configured channel: VTK
        snapshot (``outdir``) and/or Orbax checkpoint (``checkpoint_dir``)."""
        if self.outdir is not None:
            self.save_snapshot()
        if self.checkpoint_dir is not None:
            self.save_checkpoint(
                os.path.join(self.checkpoint_dir, f"step_{self.step_count:06d}")
            )

    def run(self, save: bool | None = None) -> np.ndarray:
        """Run ``cfg.steps`` steps with the reference's save cadence.

        Snapshots are written at every step index ``i < steps`` with
        ``i % save_steps == 0`` (before stepping), matching
        ``3-life/life_mpi.c:51-58``. Returns the final board.

        Robustness (all inert on the default path): periodic Orbax
        checkpoints every ``checkpoint_every`` steps; SIGTERM/SIGINT flush
        a final checkpoint at the next segment boundary and raise
        :class:`~mpi_and_open_mp_tpu.robust.preempt.Preempted`; an active
        ``MOMP_CHAOS`` plan can inject halo faults (caught by the guarded
        step) or fire a simulated preemption at a fixed step; guards are
        armed by the plan or ``MOMP_GUARD=1``.
        """
        from mpi_and_open_mp_tpu.obs import trace
        from mpi_and_open_mp_tpu.robust import chaos, guards, preempt

        cfg = self.cfg
        if save is None:
            save = self.outdir is not None or self.checkpoint_dir is not None
        # save_steps <= 0 means "never save" (the reference's 999999 idiom,
        # p46gun_big.cfg, taken to its limit); so does save=False.
        save = save and cfg.save_steps > 0
        plan = chaos.active_plan()
        guard = guards.guards_active()
        checkpointing = (
            self.checkpoint_dir is not None and self.checkpoint_every > 0
        )
        if not save and not checkpointing and plan is None and not guard:
            # The default fast path, unchanged: one advance covers the
            # whole budget, no host round trips inside it. The span (a
            # shared no-op singleton when MOMP_TRACE is unset) anchors on
            # the board so its duration covers execution, not dispatch.
            if cfg.steps > self.step_count:
                with trace.span(
                    "life.advance",
                    steps=cfg.steps - self.step_count,
                    impl=self.impl,
                    layout=self.layout,
                ) as sp:
                    self.step(cfg.steps - self.step_count)
                    sp.anchor(self.board)
            return self.collect()
        i = self.step_count
        with preempt.flush_on_signal(
                enabled=self.checkpoint_dir is not None) as sig:
            while i < cfg.steps:
                if sig.fired is not None:
                    # A real SIGTERM/SIGINT landed mid-run: flush a
                    # restart point at this segment boundary and hand the
                    # driver the exit-75 contract (preempt module docs).
                    path = (self._checkpoint_now()
                            if self.checkpoint_dir is not None else None)
                    raise preempt.Preempted(
                        i, checkpoint=path, signum=sig.fired)
                if save and i % cfg.save_steps == 0:
                    self.save_state()
                elif checkpointing and i > 0 and i % self.checkpoint_every == 0:
                    self._checkpoint_now()
                if plan is not None and plan.delay_s:
                    time.sleep(plan.delay_s)
                # Advance to the next boundary in one jit call.
                next_stop = self._next_stop(i, save)
                with trace.span(
                    "life.segment",
                    start=i,
                    stop=next_stop,
                    impl=self.impl,
                    layout=self.layout,
                    guarded=guard,
                ) as sp:
                    if guard:
                        self._guarded_step(next_stop - i)
                    else:
                        self.step(next_stop - i)
                    sp.anchor(self.board)
                prev_i, i = i, next_stop
                if (plan is not None and plan.preempt_step is not None
                        and not plan.preempt_fired
                        and prev_i < plan.preempt_step <= i):
                    plan.preempt_fired = True
                    path = (self._checkpoint_now()
                            if self.checkpoint_dir is not None else None)
                    raise preempt.SimulatedPreemption(i, checkpoint=path)
        return self.collect()
