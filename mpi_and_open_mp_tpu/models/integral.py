"""Distributed trapezoidal quadrature driver.

The TPU re-design of ``/root/reference/1-integral/integral.c``: shard the N
trapezoids over a 1-D device mesh, vectorised per-device sums, one
``lax.psum`` instead of the reference's hand-rolled Send/Recv reduction star
(``integral.c:39-43``). Keeps the driver contract: given N, print elapsed
seconds (the reference never prints the value — ``integral.c:27,44`` comment
it out — but we expose it).
"""

from __future__ import annotations

from typing import Callable

import numpy as np

import jax
from jax.sharding import Mesh

from mpi_and_open_mp_tpu.ops import quadrature
from mpi_and_open_mp_tpu.parallel import mesh as mesh_lib


class Integral:
    """∫_a^b f(x) dx by N trapezoids over a device mesh."""

    def __init__(
        self,
        n: int,
        a: float = 0.0,
        b: float = 2.0,
        f: Callable = quadrature.f_circle,
        mesh: Mesh | None = None,
    ):
        if n < 1:
            raise ValueError(f"need at least one trapezoid, got n={n}")
        self.n = int(n)  # int64 semantics: no 32-bit atoi truncation here
        self.a, self.b, self.f = float(a), float(b), f
        self.mesh = mesh if mesh is not None else mesh_lib.make_mesh_1d(axis="i")
        self._compiled = self._build()

    def _build(self):
        f, a, b, n = self.f, self.a, self.b, self.n
        axis = next(iter(self.mesh.shape))
        if self.mesh.size == 1:
            return jax.jit(lambda: quadrature.trapezoid_serial(f, a, b, n))
        smapped = mesh_lib.shard_map(
            lambda: quadrature.trapezoid_shard_sum(f, a, b, n, axis),
            mesh=self.mesh,
            in_specs=(),
            out_specs=jax.sharding.PartitionSpec(),
            check_vma=False,
        )
        return jax.jit(smapped)

    def compute(self) -> float:
        """Run the quadrature; blocks until the value is on the host."""
        return float(np.asarray(jax.device_get(self._compiled())))
