from mpi_and_open_mp_tpu.models.life import LifeSim  # noqa: F401
