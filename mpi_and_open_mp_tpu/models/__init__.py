from mpi_and_open_mp_tpu.models.life import LifeSim  # noqa: F401
from mpi_and_open_mp_tpu.models.integral import Integral  # noqa: F401
